// C++ client implementation — plain POSIX sockets, no dependencies.
// Wire protocol: ray_tpu/capi.py (length-prefixed little-endian TLV).

#include "ray_tpu/capi_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <vector>

namespace ray_tpu {
namespace {

constexpr uint8_t kPut = 2, kGet = 3, kCall = 4, kDrop = 5;
constexpr uint8_t kOk = 0;
constexpr uint32_t kVersion = 1;

void SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
    #ifdef MSG_NOSIGNAL
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);  // error, not SIGPIPE
#else
    ssize_t w = ::send(fd, p, n, 0);
#endif
    if (w <= 0) throw std::runtime_error("ray_tpu: send failed");
    p += w;
    n -= static_cast<size_t>(w);
  }
}

void RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) throw std::runtime_error("ray_tpu: connection closed");
    p += r;
    n -= static_cast<size_t>(r);
  }
}

void SendFrame(int fd, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  char header[4];
  memcpy(header, &len, 4);  // little-endian hosts only (x86/arm64)
  SendAll(fd, header, 4);
  SendAll(fd, payload.data(), payload.size());
}

std::string RecvFrame(int fd) {
  char header[4];
  RecvAll(fd, header, 4);
  uint32_t len;
  memcpy(&len, header, 4);
  std::string out(len, '\0');
  if (len) RecvAll(fd, &out[0], len);
  return out;
}

}  // namespace

Client::~Client() { Close(); }

// Default must exceed the server's longest per-request budget (300s
// CALL task wait) — a shorter recv timeout would not only fail the
// call but desynchronize the request/reply stream.
void Client::Connect(const std::string& host, int port,
                     double timeout_s) {
  Close();  // reconnecting must not leak the previous socket/session
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) != 0) {
    throw std::runtime_error("ray_tpu: cannot resolve " + host);
  }
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv;
    tv.tv_sec = static_cast<long>(timeout_s);
    tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    throw std::runtime_error("ray_tpu: cannot connect to " + host + ":" +
                             port_str);
  }
  fd_ = fd;
  std::string hello = "CAPI";
  hello.resize(8);
  memcpy(&hello[4], &kVersion, 4);
  // shared-secret auth: the token (if the cluster requires one) rides
  // after magic+version; RTPU_AUTH_TOKEN matches the head's config
  if (const char* token = ::getenv("RTPU_AUTH_TOKEN")) hello += token;
  SendFrame(fd_, hello);
  std::string reply = RecvFrame(fd_);
  if (reply.empty() || reply[0] != kOk) {
    Close();
    throw std::runtime_error("ray_tpu: handshake rejected: " +
                             reply.substr(1));
  }
}

std::string Client::Request(uint8_t kind, const std::string& body) {
  if (fd_ < 0) throw std::runtime_error("ray_tpu: not connected");
  std::string frame(1, static_cast<char>(kind));
  frame += body;
  std::string reply;
  try {
    SendFrame(fd_, frame);
    reply = RecvFrame(fd_);
  } catch (...) {
    // A transport failure (incl. recv timeout) desynchronizes the
    // request/reply stream: a later request would read this one's
    // late reply as its own. Poison the connection instead.
    Close();
    throw;
  }
  if (reply.empty()) {
    Close();
    throw std::runtime_error("ray_tpu: empty reply");
  }
  if (reply[0] != kOk) {
    // server-reported error: the stream stays aligned, keep the fd
    throw std::runtime_error("ray_tpu: " + reply.substr(1));
  }
  return reply.substr(1);
}

std::string Client::Put(const std::string& payload) {
  std::string id = Request(kPut, payload);
  if (id.size() != 16) throw std::runtime_error("ray_tpu: bad object id");
  return id;
}

std::string Client::Get(const std::string& object_id) {
  return Request(kGet, object_id);
}

std::string Client::Call(const std::string& name,
                         const std::string& args) {
  if (name.size() > 0xFFFF) {
    throw std::runtime_error("ray_tpu: function name too long");
  }
  uint16_t n = static_cast<uint16_t>(name.size());
  std::string body(2, '\0');
  memcpy(&body[0], &n, 2);
  body += name;
  body += args;
  return Request(kCall, body);
}

void Client::Drop(const std::string& object_id) {
  Request(kDrop, object_id);
}

void Client::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

}  // namespace ray_tpu
