// C++ worker runtime — executes registered C++ functions/actors for
// the cluster (wire protocol: ray_tpu/capi.py kinds 6 EXEC-register,
// 7 EXEC, 8 RESULT; reference capability: C++ workers behind
// cpp/include/ray/api.h). Plain POSIX sockets, no dependencies.

#include "ray_tpu/worker_api.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <vector>

namespace ray_tpu {
namespace {

constexpr uint8_t kWorkerRegister = 6, kExec = 7, kResult = 8;
constexpr uint8_t kOk = 0, kErr = 1;
constexpr uint8_t kOpFn = 0, kOpActorNew = 1, kOpActorCall = 2,
                  kOpActorDel = 3;
constexpr uint32_t kVersion = 1;

std::map<std::string, TaskFn>& Functions() {
  static std::map<std::string, TaskFn> fns;
  return fns;
}

std::map<std::string, ActorFactory>& ActorClasses() {
  static std::map<std::string, ActorFactory> classes;
  return classes;
}

void SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
#ifdef MSG_NOSIGNAL
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
#else
    ssize_t w = ::send(fd, p, n, 0);
#endif
    if (w <= 0) throw std::runtime_error("ray_tpu worker: send failed");
    p += w;
    n -= static_cast<size_t>(w);
  }
}

bool RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;  // head closed: clean shutdown
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void SendFrame(int fd, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  char header[4];
  memcpy(header, &len, 4);  // little-endian hosts (x86/arm64)
  SendAll(fd, header, 4);
  SendAll(fd, payload.data(), payload.size());
}

bool RecvFrame(int fd, std::string* out) {
  char header[4];
  if (!RecvAll(fd, header, 4)) return false;
  uint32_t len;
  memcpy(&len, header, 4);
  out->assign(len, '\0');
  return len == 0 || RecvAll(fd, &(*out)[0], len);
}

void Append(std::string* s, const void* data, size_t n) {
  s->append(static_cast<const char*>(data), n);
}

}  // namespace

void RegisterFunction(const std::string& name, TaskFn fn) {
  Functions()[name] = std::move(fn);
}

void RegisterActorClass(const std::string& name, ActorFactory factory) {
  ActorClasses()[name] = std::move(factory);
}

WorkerRuntime::WorkerRuntime(const std::string& host, int port) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) != 0) {
    throw std::runtime_error("ray_tpu worker: cannot resolve " + host);
  }
  fd_ = -1;
  for (auto* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd_ = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd_ < 0) continue;
    if (::connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd_);
    fd_ = -1;
  }
  freeaddrinfo(res);
  if (fd_ < 0) throw std::runtime_error("ray_tpu worker: connect failed");

  // magic handshake (+ shared-secret token when the cluster requires
  // one), then register every compiled-in entry point
  std::string magic = "CAPI";
  Append(&magic, &kVersion, 4);
  if (const char* token = ::getenv("RTPU_AUTH_TOKEN")) magic += token;
  SendFrame(fd_, magic);
  std::string ack;
  if (!RecvFrame(fd_, &ack) || ack.empty() || ack[0] != kOk) {
    throw std::runtime_error("ray_tpu worker: handshake rejected");
  }

  std::string reg;
  reg.push_back(static_cast<char>(kWorkerRegister));
  uint16_t count = static_cast<uint16_t>(Functions().size()
                                         + ActorClasses().size());
  Append(&reg, &count, 2);
  auto add_entry = [&reg](uint8_t entry_kind, const std::string& name) {
    reg.push_back(static_cast<char>(entry_kind));
    uint16_t len = static_cast<uint16_t>(name.size());
    Append(&reg, &len, 2);
    reg += name;
  };
  for (const auto& kv : Functions()) add_entry(0, kv.first);
  for (const auto& kv : ActorClasses()) add_entry(1, kv.first);
  SendFrame(fd_, reg);
  if (!RecvFrame(fd_, &ack) || ack.empty() || ack[0] != kOk) {
    throw std::runtime_error("ray_tpu worker: registration rejected");
  }
}

WorkerRuntime::~WorkerRuntime() {
  if (fd_ >= 0) ::close(fd_);
}

void WorkerRuntime::Run() {
  std::string frame;
  while (RecvFrame(fd_, &frame)) {
    if (frame.empty() || frame[0] != kExec) continue;
    // EXEC: u64 call_id, u8 op, u64 instance_id, u16 name_len, name,
    // args
    uint64_t call_id, instance_id;
    uint8_t op;
    uint16_t name_len;
    size_t off = 1;
    memcpy(&call_id, frame.data() + off, 8), off += 8;
    memcpy(&op, frame.data() + off, 1), off += 1;
    memcpy(&instance_id, frame.data() + off, 8), off += 8;
    memcpy(&name_len, frame.data() + off, 2), off += 2;
    std::string name = frame.substr(off, name_len);
    std::string args = frame.substr(off + name_len);

    uint8_t status = kOk;
    std::string payload;
    try {
      if (op == kOpFn) {
        auto it = Functions().find(name);
        if (it == Functions().end()) {
          throw std::runtime_error("unknown function " + name);
        }
        payload = it->second(args);
      } else if (op == kOpActorNew) {
        auto it = ActorClasses().find(name);
        if (it == ActorClasses().end()) {
          throw std::runtime_error("unknown actor class " + name);
        }
        uint64_t id = next_instance_++;
        instances_[id] = it->second(args);
        Append(&payload, &id, 8);
      } else if (op == kOpActorCall) {
        auto it = instances_.find(instance_id);
        if (it == instances_.end()) {
          throw std::runtime_error("dead or unknown actor instance");
        }
        payload = it->second->Call(name, args);
      } else if (op == kOpActorDel) {
        instances_.erase(instance_id);
      } else {
        throw std::runtime_error("unknown op");
      }
    } catch (const std::exception& e) {
      status = kErr;
      payload = e.what();
    }

    std::string result;
    result.push_back(static_cast<char>(kResult));
    Append(&result, &call_id, 8);
    result.push_back(static_cast<char>(status));
    result += payload;
    SendFrame(fd_, result);
  }
}

}  // namespace ray_tpu
