// End-to-end exercise of the C++ client API against a live head.
// Driven by tests/test_cpp_api.py: argv = host port.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "ray_tpu/capi_client.h"

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s host port\n", argv[0]);
    return 64;
  }
  ray_tpu::Client client;
  client.Connect(argv[1], atoi(argv[2]));

  // put/get roundtrip, including binary payloads with NULs
  std::string payload("bin\0ary\xff payload", 16);
  std::string id = client.Put(payload);
  if (client.Get(id) != payload) {
    fprintf(stderr, "FAIL: get != put\n");
    return 2;
  }

  // large object (beyond the inline cap: exercises the arena path)
  std::string big(1 << 20, 'x');
  std::string big_id = client.Put(big);
  if (client.Get(big_id) != big) {
    fprintf(stderr, "FAIL: 1MB roundtrip\n");
    return 2;
  }
  client.Drop(big_id);

  // call a registered Python function, executed as a cluster task
  std::string doubled = client.Call("double", "ab");
  if (doubled != "abab") {
    fprintf(stderr, "FAIL: Call returned %s\n", doubled.c_str());
    return 2;
  }

  // errors surface as exceptions, connection stays usable after
  bool threw = false;
  try {
    client.Call("no-such-fn", "");
  } catch (const std::runtime_error&) {
    threw = true;
  }
  if (!threw) {
    fprintf(stderr, "FAIL: missing function did not throw\n");
    return 2;
  }
  if (client.Get(id) != payload) {
    fprintf(stderr, "FAIL: connection unusable after error\n");
    return 2;
  }
  client.Drop(id);
  client.Close();
  printf("CPP-OK\n");
  return 0;
}
