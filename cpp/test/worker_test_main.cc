// Test C++ worker: one function + one stateful actor, driven by
// tests/test_cpp_api.py against a live head.

#include <unistd.h>

#include <cstdlib>
#include <stdexcept>
#include <string>

#include "ray_tpu/worker_api.h"

static std::string Add(const std::string& args) {
  // args = "a,b" decimal ints
  auto comma = args.find(',');
  long a = std::stol(args.substr(0, comma));
  long b = std::stol(args.substr(comma + 1));
  return std::to_string(a + b);
}
RAY_TPU_REMOTE(Add);

static std::string Fail(const std::string& args) {
  throw std::runtime_error("intentional C++ failure: " + args);
}
RAY_TPU_REMOTE(Fail);

class Counter : public ray_tpu::Actor {
 public:
  std::string Call(const std::string& method,
                   const std::string& args) override {
    if (method == "incr") {
      total_ += std::stol(args);
      return std::to_string(total_);
    }
    if (method == "get") return std::to_string(total_);
    if (method == "slow") {
      // Parks this worker so a kill-mid-flight test has a call that
      // is deterministically still pending when the worker dies.
      sleep(30);
      return "slow-done";
    }
    throw std::runtime_error("unknown method " + method);
  }

 private:
  long total_ = 0;
};
RAY_TPU_ACTOR(Counter);

int main(int argc, char** argv) {
  const char* host = argc > 1 ? argv[1] : "127.0.0.1";
  int port = argc > 2 ? std::atoi(argv[2]) : 6379;
  ray_tpu::WorkerRuntime rt(host, port);
  rt.Run();
  return 0;
}
