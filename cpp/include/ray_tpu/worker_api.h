// ray_tpu C++ worker API — run C++ tasks and actors in a C++ worker
// process (reference capability: cpp/include/ray/api.h — RAY_REMOTE
// registration + ray::Task(...).Remote() executing in C++ workers; the
// design here is ray_tpu's TLV worker channel, ray_tpu/capi.py kinds
// 6/7/8).
//
//   static std::string Add(const std::string& args) { ... }
//   RAY_TPU_REMOTE(Add);
//
//   class Counter : public ray_tpu::Actor {
//    public:
//     std::string Call(const std::string& method,
//                      const std::string& args) override;
//   };
//   RAY_TPU_ACTOR(Counter);
//
//   int main() {
//     ray_tpu::WorkerRuntime rt("127.0.0.1", 6379);
//     rt.Run();  // serve executions until the head disconnects
//   }

#ifndef RAY_TPU_WORKER_API_H_
#define RAY_TPU_WORKER_API_H_

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>

namespace ray_tpu {

using TaskFn = std::function<std::string(const std::string&)>;

// Stateful C++ actor: one instance per actor_new, methods dispatched
// by name through Call. Executions on one instance are serialized by
// the worker's single-threaded loop (the ordering guarantee actors
// need).
class Actor {
 public:
  virtual ~Actor() = default;
  virtual std::string Call(const std::string& method,
                           const std::string& args) = 0;
};

using ActorFactory =
    std::function<std::unique_ptr<Actor>(const std::string& args)>;

// Process-wide registries (populated before WorkerRuntime::Run).
void RegisterFunction(const std::string& name, TaskFn fn);
void RegisterActorClass(const std::string& name, ActorFactory factory);

namespace internal {
struct Registrar {
  Registrar(const std::string& name, TaskFn fn) {
    RegisterFunction(name, std::move(fn));
  }
  Registrar(const std::string& name, ActorFactory factory) {
    RegisterActorClass(name, std::move(factory));
  }
};
}  // namespace internal

#define RAY_TPU_REMOTE(fn) \
  static ::ray_tpu::internal::Registrar ray_tpu_reg_##fn(#fn, fn)

#define RAY_TPU_ACTOR(cls)                                          \
  static ::ray_tpu::internal::Registrar ray_tpu_actor_##cls(        \
      #cls, ::ray_tpu::ActorFactory([](const std::string& args) {   \
        (void)args;                                                 \
        return std::unique_ptr<::ray_tpu::Actor>(new cls());        \
      }))

// Connects to the head's TCP listener as a C++ worker, registers every
// function/actor class, then serves EXEC frames until disconnect.
class WorkerRuntime {
 public:
  WorkerRuntime(const std::string& host, int port);
  ~WorkerRuntime();

  // Blocks; returns when the head closes the connection.
  void Run();

 private:
  int fd_;
  uint64_t next_instance_ = 1;
  std::map<uint64_t, std::unique_ptr<Actor>> instances_;
};

}  // namespace ray_tpu

#endif  // RAY_TPU_WORKER_API_H_
