// ray_tpu C++ client API.
//
// Capability analog of the reference's C++ public API
// (reference: cpp/include/ray/api.h — Put/Get/Task). Divergence,
// stated plainly: the reference embeds a C++ core worker that executes
// C++ tasks; this is a CLIENT library — it connects to a running
// cluster head over TCP (the same listener node daemons and Python
// clients use), puts/gets byte objects, and invokes Python functions
// registered via ray_tpu.capi.register_function, executed as ordinary
// cluster tasks. Wire protocol: ray_tpu/capi.py docstring.
//
//   ray_tpu::Client client;
//   client.Connect("127.0.0.1", 6379);
//   auto id  = client.Put("hello");
//   auto val = client.Get(id);            // "hello"
//   auto out = client.Call("double", "ab");  // python fn, as a task
//   client.Drop(id);
//
// Every method throws std::runtime_error on failure. Header-only
// client struct; implementation in cpp/src/capi_client.cc.

#pragma once

#include <cstdint>
#include <stdexcept>
#include <string>

namespace ray_tpu {

class Client {
 public:
  Client() = default;
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Connect + handshake (magic frame, version check). timeout_s is
  // the per-syscall send/recv timeout; it must exceed the longest
  // server-side request budget (CALL waits up to 300s on the task).
  void Connect(const std::string& host, int port,
               double timeout_s = 330.0);

  // Store a byte object on the cluster; returns its 16-byte id.
  std::string Put(const std::string& payload);

  // Fetch a byte object (created here or by any Python task).
  std::string Get(const std::string& object_id);

  // Invoke a registered Python function (bytes -> bytes) as a task.
  std::string Call(const std::string& name, const std::string& args);

  // Release this client's reference to an object it Put().
  void Drop(const std::string& object_id);

  void Close();

 private:
  std::string Request(uint8_t kind, const std::string& body);
  int fd_ = -1;
};

}  // namespace ray_tpu
