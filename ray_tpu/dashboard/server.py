"""Dashboard HTTP server: REST state API + metrics + logs + HTML index.

Capability parity with the reference's dashboard head server and its
modules (reference: python/ray/dashboard/head.py; modules/node, actor,
job, log, metrics; state aggregation via state_aggregator.py → the
``ray.util.state`` API). Routes:

  GET /                      — web UI (vanilla-JS SPA, client.html)
  GET /api/cluster           — resources total/available, head address
  GET /api/nodes             — node table
  GET /api/actors            — actor table
  GET /api/tasks?limit=N     — latest task events
  GET /api/summary           — task-state counts
  GET /api/objects           — referenced objects
  GET /api/placement_groups  — placement groups
  GET /api/jobs              — driver + submitted jobs
  GET /api/events            — cluster lifecycle events
                               (?kind=A,B&severity=MIN&limit=N&
                                node_id=&actor_id=&since_seq=)
  GET /api/logs              — log files per node log dir
  GET /api/logs/tail?file=F&lines=N[&follow=1] — tail (SSE when follow)
  GET /metrics               — Prometheus exposition text
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

_client_html_cache: Optional[str] = None


def _client_html() -> str:
    """The web UI is a standalone vanilla-JS SPA (reference capability:
    the React client under python/ray/dashboard/client — multi-view
    cluster console; here dependency-free, served from one file).
    Loaded lazily on the first GET / so a missing file degrades that
    request, never the dashboard module import (which ray_tpu.init
    performs even with the dashboard disabled)."""
    global _client_html_cache
    if _client_html_cache is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "client.html")
        with open(path, encoding="utf-8") as f:
            _client_html_cache = f.read()
    return _client_html_cache


class DashboardServer:
    """Serves cluster state over HTTP from inside the driver process
    (the control plane lives here, so reads are direct — the reference's
    aggregation hop from GCS to the dashboard head collapses away)."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self._runtime = runtime
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request noise
                pass

            def do_GET(self):
                try:
                    dashboard._route(self)
                except BrokenPipeError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    try:
                        self.send_error(500, str(exc))
                    except (OSError, ValueError):
                        pass  # client already hung up

            def do_POST(self):
                try:
                    dashboard._route_post(self)
                except BrokenPipeError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    try:
                        self.send_error(500, str(exc))
                    except (OSError, ValueError):
                        pass  # client already hung up

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _log_dirs(self) -> List[str]:
        dirs = []
        for node in self._runtime.nodes.values():
            d = os.path.join(node.session_dir, "logs")
            if os.path.isdir(d):
                dirs.append(d)
        return dirs

    def _resolve_log(self, name: str) -> Optional[str]:
        """Map a client-supplied file name onto a real log file —
        basename-only, so requests can't traverse the filesystem."""
        base = os.path.basename(name)
        for d in self._log_dirs():
            full = os.path.join(d, base)
            if os.path.isfile(full):
                return full
        return None

    def _route_post(self, req: BaseHTTPRequestHandler) -> None:
        """REST mutations (reference: serve REST surface,
        PUT/POST /api/serve/applications on the dashboard agent)."""
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/")
        if path == "/api/serve/deploy":
            length = int(req.headers.get("Content-Length", 0))
            body = req.rfile.read(length)
            try:
                config = json.loads(body or b"{}")
            except ValueError:
                return req.send_error(400, "request body is not JSON")
            from ray_tpu.serve.schema import deploy_config
            try:
                deployed = deploy_config(config)
            except (ValueError, TypeError) as exc:
                # config errors are the CLIENT's fault: 400, with the
                # validation message intact (a 500 would read as a
                # dashboard fault and invite retries of a bad config)
                return req.send_error(400, str(exc))
            return self._send_json(req, {"deployed": deployed})
        req.send_error(404, "unknown route")

    def _route(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        from ray_tpu.util import state as state_api

        if path == "/":
            return self._send(req, _client_html(), "text/html")
        if path == "/metrics":
            from ray_tpu.util.metrics import prometheus_text
            self._record_core_metrics()
            return self._send(req, prometheus_text(),
                              "text/plain; version=0.0.4")
        if path == "/api/cluster":
            rt = self._runtime
            return self._send_json(req, {
                "head_address": getattr(rt, "head_address", None),
                "total": rt.cluster_resources(),
                "available": rt.available_resources(),
                "dashboard_url": self.url,
            })
        if path == "/api/nodes":
            return self._send_json(req, state_api.list_nodes())
        if path == "/api/actors":
            return self._send_json(req, state_api.list_actors())
        if path == "/api/tasks":
            limit = int(query.get("limit", 1000))
            return self._send_json(req, state_api.list_tasks(limit=limit))
        if path == "/api/summary":
            return self._send_json(req, state_api.summarize_tasks())
        if path == "/api/objects":
            return self._send_json(req, state_api.list_objects())
        if path == "/api/placement_groups":
            return self._send_json(req, state_api.list_placement_groups())
        if path == "/api/jobs":
            return self._send_json(req, state_api.list_jobs())
        if path == "/api/events":
            kinds = query.get("kind")
            return self._send_json(req, state_api.list_cluster_events(
                limit=int(query.get("limit", 1000)),
                kinds=kinds.split(",") if kinds else None,
                severity=query.get("severity"),
                node_id=query.get("node_id"),
                worker_id=query.get("worker_id"),
                actor_id=query.get("actor_id"),
                task_id=query.get("task_id"),
                since_seq=(int(query["since_seq"])
                           if "since_seq" in query else None)))
        if path == "/api/timeline":
            from ray_tpu.util.timeline import chrome_trace_events
            return self._send_json(
                req, chrome_trace_events(self._runtime))
        if path == "/api/profile":
            # sampling-profiler snapshot (devtools/profiler.py):
            # per-process folded stacks for the SPA flamegraph;
            # ?proc=<label> narrows to one process
            from ray_tpu.devtools import profiler
            proc = query.get("proc")
            profiles = profiler.merged_profiles()
            return self._send_json(req, {
                "enabled": profiler.enabled() or bool(profiles),
                "procs": sorted(profiles),
                "samples": {label: snap.get("samples", 0)
                            for label, snap in profiles.items()},
                "folded": profiler.folded(proc),
            })
        if path == "/api/traces":
            return self._send_json(req, self._trace_index())
        if path.startswith("/api/traces/"):
            trace_id = path.rsplit("/", 1)[1]
            return self._send_json(req, self._trace_detail(trace_id))
        if path == "/api/serve":
            return self._send_json(req, self._serve_status())
        if path == "/api/train":
            return self._send_json(req, self._train_runs())
        if path == "/api/logs":
            files = {}
            for d in self._log_dirs():
                files[d] = sorted(
                    name for name in os.listdir(d)
                    if name.endswith(".log"))
            return self._send_json(req, files)
        if path == "/api/logs/tail":
            return self._tail_log(req, query)
        req.send_error(404, "unknown route")

    def _record_core_metrics(self) -> None:
        """Refresh runtime gauges on every /metrics scrape so the SPA's
        time-series view (and any Prometheus scraper) sees live task
        counters, per-node object-store bytes, and per-deployment
        request totals (reference: dashboard/modules/metrics +
        metrics_agent.py exporting core state)."""
        from ray_tpu.util.metrics import Gauge
        if not hasattr(self, "_core_gauges"):
            self._core_gauges = {
                "finished": Gauge("ray_tpu_tasks_finished_total",
                                  "Lifetime finished tasks"),
                "failed": Gauge("ray_tpu_tasks_failed_total",
                                "Lifetime failed tasks"),
                "pending": Gauge("ray_tpu_tasks_pending",
                                 "Currently pending tasks"),
                "store": Gauge("ray_tpu_object_store_used_bytes",
                               "Object store bytes in use",
                               tag_keys=("node",)),
                "serve_total": Gauge(
                    "ray_tpu_serve_requests_total",
                    "Lifetime serve requests", tag_keys=("deployment",)),
            }
        from ray_tpu.util.metrics import remove_series
        g = self._core_gauges
        rt = self._runtime
        tm = rt.task_manager
        g["finished"].set(float(getattr(tm, "num_finished", 0)))
        g["failed"].set(float(getattr(tm, "num_failed", 0)))
        g["pending"].set(float(tm.num_pending()))
        store_tags = set()
        for node_id, node in list(rt.nodes.items()):
            used = (node.store.used_bytes()
                    if getattr(node, "store", None) is not None
                    and hasattr(node.store, "used_bytes")
                    else getattr(node, "store_used", 0))
            tag = node_id.hex()[:12]
            store_tags.add(tag)
            g["store"].set(float(used or 0), tags={"node": tag})
        # dead nodes' series must stop being exported (zombie charts)
        for tag in getattr(self, "_prev_store_tags", set()) - store_tags:
            remove_series("ray_tpu_object_store_used_bytes",
                          {"node": tag})
        self._prev_store_tags = store_tags
        # Serve totals fan out to replica actors — cache briefly so
        # overlapping scrapes (SPA poll + Prometheus) don't multiply
        # the round trips, and keep the whole probe off this thread's
        # critical path budget.
        now = time.time()
        cached = getattr(self, "_serve_totals_cache", None)
        if cached is not None and now - cached[0] < 3.0:
            totals = cached[1]
        else:
            totals = None
            try:
                import ray_tpu
                from ray_tpu.serve.controller import CONTROLLER_NAME
                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                totals = ray_tpu.get(
                    controller.get_request_totals.remote(), timeout=10)
            except Exception:  # noqa: BLE001 — serve not running
                totals = {} if cached is None else None
            if totals is not None:
                self._serve_totals_cache = (now, totals)
            else:
                totals = cached[1]  # probe failed: keep last values
        serve_tags = set()
        for name, total in totals.items():
            serve_tags.add(name)
            g["serve_total"].set(total, tags={"deployment": name})
        for name in (getattr(self, "_prev_serve_tags", set())
                     - serve_tags):
            remove_series("ray_tpu_serve_requests_total",
                          {"deployment": name})
        self._prev_serve_tags = serve_tags

    def _trace_index(self):
        """Recent trace ids with span counts (newest first)."""
        gcs = self._runtime.gcs
        out = []
        for trace_id in gcs.recent_trace_ids(limit=100):
            out.append({"trace_id": trace_id,
                        "spans": len(gcs.spans_for_trace(trace_id))})
        return out

    def _trace_detail(self, trace_id: str):
        """One distributed trace: recorded spans (proxy/router/replica/
        engine hops, user tracing.span blocks) merged with the task
        events carrying this trace_id — every ``.remote()`` made while
        handling the traced request shows up here."""
        gcs = self._runtime.gcs
        spans = []
        for (tid, span_id, parent_span_id, name, component, t_start,
             duration, tags) in gcs.spans_for_trace(trace_id):
            spans.append({
                "span_id": span_id, "parent_span_id": parent_span_id,
                "name": name, "component": component,
                "start": t_start, "duration": duration,
                "tags": tags or {},
            })
        task_events = []
        from ray_tpu.util.tracing import task_span_id
        for ev in gcs.events_for_trace(trace_id):
            task_events.append({
                "task_id": ev.task_id.hex(),
                "span_id": task_span_id(ev.task_id),
                "name": ev.name, "state": ev.state,
                "timestamp": ev.timestamp, "duration": ev.duration,
                "node_id": ev.node_id.hex() if ev.node_id else None,
                "error": ev.error,
            })
            if ev.state == "RUNNING" and ev.duration is not None:
                # a task's execution is a span of the trace too
                spans.append({
                    "span_id": task_span_id(ev.task_id),
                    "parent_span_id": None,
                    "name": ev.name, "component": "task",
                    "start": ev.timestamp, "duration": ev.duration,
                    "tags": {"task_id": ev.task_id.hex()},
                })
        spans.sort(key=lambda s: s["start"])
        return {"trace_id": trace_id, "spans": spans,
                "task_events": task_events}

    def _serve_status(self):
        """Deployment/replica status from the serve controller
        (reference: dashboard/modules/serve)."""
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            return ray_tpu.get(controller.get_status.remote(), timeout=10)
        except Exception:  # noqa: BLE001 — serve not running
            return {}

    def _train_runs(self):
        """Train run states published by JaxTrainer (reference:
        dashboard/modules/train)."""
        from ray_tpu.core import serialization
        gcs = self._runtime.gcs
        out = []
        for key in gcs.kv.keys(namespace="train_runs"):
            blob = gcs.kv.get(key, namespace="train_runs")
            if blob:
                out.append(serialization.loads(blob))
        out.sort(key=lambda r: -r.get("updated_at", 0))
        return out

    def _tail_log(self, req, query) -> None:
        name = query.get("file", "")
        path = self._resolve_log(name)
        if path is None:
            return req.send_error(404, f"log file not found: {name}")
        lines = int(query.get("lines", 100))
        # bounded read: never load a multi-GB log into the driver —
        # seek to a generous per-line budget from the end
        bound = min(lines * 4096, 8 * 1024 * 1024)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - bound))
            data = f.read(bound)
        offset_base = max(0, size - bound)
        tail = b"\n".join(data.splitlines()[-lines:])
        if not query.get("follow"):
            return self._send(req, tail.decode("utf-8", "replace"),
                              "text/plain")
        # follow: SSE stream of appended chunks until the client leaves
        # (reference: dashboard log streaming over websockets; SSE keeps
        # the stdlib server sufficient)
        req.send_response(200)
        req.send_header("Content-Type", "text/event-stream")
        req.send_header("Cache-Control", "no-cache")
        req.end_headers()
        offset = offset_base + len(data)
        for line in tail.splitlines():
            req.wfile.write(b"data: " + line + b"\n\n")
        req.wfile.flush()
        deadline = time.time() + float(query.get("timeout", 300))
        while time.time() < deadline:
            try:
                size = os.path.getsize(path)
                if size > offset:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        chunk = f.read(size - offset)
                    offset = size
                    for line in chunk.splitlines():
                        req.wfile.write(b"data: " + line + b"\n\n")
                    req.wfile.flush()
                else:
                    time.sleep(0.25)
            except (BrokenPipeError, ConnectionResetError, OSError):
                return

    # ------------------------------------------------------------------
    @staticmethod
    def _send(req, body: str, content_type: str) -> None:
        payload = body.encode()
        req.send_response(200)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(payload)))
        req.end_headers()
        req.wfile.write(payload)

    @classmethod
    def _send_json(cls, req, obj) -> None:
        cls._send(req, json.dumps(obj), "application/json")
