"""Log monitor: tail worker log files, stream new lines to the driver.

Capability parity with the reference's log monitor
(reference: python/ray/_private/log_monitor.py — tails the session log
dir and publishes new lines; python/ray/_private/worker.py:2266
print_worker_logs renders them with a per-worker prefix).

Workers write stdout+stderr to ``{session_dir}/logs/worker-<id>.log``
(ray_tpu/core/node.py spawn path). One monitor thread per driver scans
the directory, remembers per-file offsets, and echoes appended content
to the driver's stdout prefixed with the worker id. The same files back
the dashboard's ``/api/logs`` endpoints.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Dict, List

logger = logging.getLogger(__name__)


class LogMonitor:
    def __init__(self, log_dirs: List[str], echo: bool = True,
                 interval_s: float = 0.2):
        self._log_dirs = list(log_dirs)
        self._echo = echo
        self._interval_s = interval_s
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="log-monitor", daemon=True)
        self._thread.start()

    def add_dir(self, log_dir: str) -> None:
        self._log_dirs.append(log_dir)

    def stop(self) -> None:
        self._stopped.set()

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                logger.exception("log monitor poll failed; retrying")

    def poll_once(self) -> None:
        for log_dir in list(self._log_dirs):
            if not os.path.isdir(log_dir):
                continue
            for name in sorted(os.listdir(log_dir)):
                if not name.endswith(".log"):
                    continue
                self._drain(os.path.join(log_dir, name))

    def _drain(self, path: str) -> None:
        offset = self._offsets.get(path, 0)
        try:
            size = os.path.getsize(path)
            if size < offset:
                offset = 0  # file rotated/truncated: start over
                # drop any dangling pre-rotation line fragment — it
                # must not splice onto the new file's first line
                self._partial.pop(path, None)
            if size == offset:
                return
            if not self._echo:
                # nothing consumes the bytes (dashboard serves the files
                # directly) — just advance past them
                self._offsets[path] = size
                return
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size - offset)
        except OSError:
            return
        self._offsets[path] = offset + len(data)
        # line-buffer across reads so a worker's partial line isn't
        # printed split under two prefixes
        data = self._partial.pop(path, b"") + data
        lines = data.split(b"\n")
        if lines and lines[-1]:
            self._partial[path] = lines[-1]
        prefix = f"({os.path.basename(path)[:-4]}) "
        out = "".join(
            prefix + line.decode("utf-8", "replace") + "\n"
            for line in lines[:-1])
        if out:
            sys.stdout.write(out)
            sys.stdout.flush()
