"""Dashboard: HTTP observability plane for a running cluster.

Capability parity with the reference's dashboard head + modules
(reference: python/ray/dashboard/head.py, modules/{node,actor,job,
metrics,log}/ and the state aggregator state_aggregator.py) — minus the
React frontend: the UI here is one self-contained HTML page over the
same REST API the CLI and state API use.

Components:
  server.py      — DashboardServer: REST API + /metrics + HTML index
  log_monitor.py — tails per-worker log files, echoes to the driver
                   (reference: python/ray/_private/log_monitor.py)
"""

from ray_tpu.dashboard.server import DashboardServer

__all__ = ["DashboardServer"]
