"""Public API: init/remote/get/put/wait and friends.

Capability parity with the reference's top-level API
(reference: python/ray/_private/worker.py — init:1427, get:2852,
put:2995, wait, kill, cancel; python/ray/__init__.py exports).
"""

from __future__ import annotations

import functools
import inspect
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor
from ray_tpu.core.ids import NodeID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.runtime import DriverRuntime


def init(*, address: Optional[str] = None,
         num_cpus: Optional[int] = None, num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "",
         system_config: Optional[dict] = None,
         head_port: Optional[int] = None,
         include_dashboard: bool = True,
         dashboard_port: int = 0,
         ignore_reinit_error: bool = False) -> DriverRuntime:
    """Start the head runtime (worker pool + object store + scheduler).

    ``head_port`` >= 0 additionally opens the multi-host control plane:
    a TCP listener node daemons join via ``ray-tpu start --address``
    (0 picks a free port; see ``runtime.head_address``).

    ``include_dashboard`` starts the HTTP dashboard (REST state API +
    /metrics + log tail; see ray_tpu/dashboard/) on ``dashboard_port``
    (0 = ephemeral; URL at ``runtime.dashboard_url``) and the log
    monitor that echoes worker logs to this process when the
    ``log_to_driver`` flag is set.
    """
    existing = runtime_mod.get_runtime_or_none()
    if existing is not None:
        if ignore_reinit_error:
            return existing
        raise RuntimeError("ray_tpu is already initialized; call shutdown() first")
    if address is not None:
        # CLIENT MODE (reference: Ray Client, python/ray/util/client/):
        # this process becomes a remote driver proxied through the
        # head's TCP listener; no local services start.
        from ray_tpu.core.client import ClientRuntime
        rt = ClientRuntime(address, namespace=namespace)
        runtime_mod.set_runtime(rt)
        return rt
    if head_port is not None:
        system_config = dict(system_config or {})
        system_config.setdefault("head_port", head_port)
    res = dict(resources or {})
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    rt = DriverRuntime(resources=res or None, labels=labels,
                       object_store_memory=object_store_memory,
                       system_config=system_config, namespace=namespace)
    runtime_mod.set_runtime(rt)
    rt._shutdown_hooks = []
    rt.dashboard_url = None
    # The log monitor is how worker prints reach the driver at all now
    # that worker stdout/stderr go to session log files — it must run
    # regardless of the dashboard.
    from ray_tpu.core.config import get_config
    from ray_tpu.dashboard.log_monitor import LogMonitor
    log_dirs = [os.path.join(node.session_dir, "logs")
                for node in rt.nodes.values()]
    monitor = LogMonitor(log_dirs, echo=get_config().log_to_driver)
    rt._log_monitor = monitor
    rt._shutdown_hooks.append(monitor.stop)
    if include_dashboard:
        try:
            from ray_tpu.dashboard import DashboardServer
            dashboard = DashboardServer(rt, port=dashboard_port)
            rt.dashboard_url = dashboard.url
            rt._shutdown_hooks.append(dashboard.stop)
        except OSError:
            # a dashboard bind failure must never block init
            pass
    return rt


def shutdown() -> None:
    rt = runtime_mod.get_runtime_or_none()
    if rt is None:
        return
    if getattr(rt, "is_driver", False):
        rt.shutdown()
    elif getattr(rt, "is_client", False):
        rt.shutdown()
        runtime_mod.set_runtime(None)


def is_initialized() -> bool:
    return runtime_mod.get_runtime_or_none() is not None


def remote(*args, **options):
    """Decorator turning a function into a RemoteFunction or a class into
    an ActorClass. Usable bare (``@remote``) or with options
    (``@remote(num_cpus=2)``)."""
    def make(target):
        if inspect.isclass(target):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    if len(args) == 1 and not options and (inspect.isfunction(args[0])
                                           or inspect.isclass(args[0])):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    return runtime_mod.get_runtime().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return runtime_mod.get_runtime().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return runtime_mod.get_runtime().wait(
        list(refs), num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    runtime_mod.get_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    runtime_mod.get_runtime().cancel(ref.id, force=force)


def cluster_resources() -> Dict[str, float]:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt.cluster_resources()
    return rt.gcs_call("cluster_resources")


def available_resources() -> Dict[str, float]:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt.available_resources()
    return rt.gcs_call("available_resources")


def nodes() -> List[dict]:
    # one record shape for every mode: driver dispatches directly,
    # workers/clients go through their GCS bridge
    return runtime_mod.get_runtime().gcs_call("list_nodes")


class _RuntimeContext:
    """reference: python/ray/runtime_context.py"""

    @property
    def is_initialized(self) -> bool:
        return is_initialized()

    def get_node_id(self) -> Optional[str]:
        rt = runtime_mod.get_runtime_or_none()
        if rt is None:
            return None
        if rt.is_driver:
            from ray_tpu.core.virtual_node import current_virtual_node_id
            vnode_id = current_virtual_node_id()
            if vnode_id is not None:  # executing ON a virtual member
                return vnode_id.hex()
            return rt.head_node_id.hex()
        node_id = getattr(rt, "node_id", None)
        return node_id.hex() if node_id is not None else None  # client

    def get_actor_id(self) -> Optional[str]:
        rt = runtime_mod.get_runtime_or_none()
        actor_id = getattr(rt, "actor_id", None)
        return actor_id.hex() if actor_id else None

    def get_job_id(self) -> Optional[str]:
        rt = runtime_mod.get_runtime_or_none()
        job_id = getattr(rt, "job_id", None)
        return job_id.hex() if job_id else None

    def get_task_id(self) -> Optional[str]:
        from ray_tpu.core.remote_function import submitting_task_id
        rt = runtime_mod.get_runtime_or_none()
        task_id = submitting_task_id(rt) if rt is not None else None
        return task_id.hex() if task_id else None


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()


def timeline(filename: Optional[str] = None,
             trace_id: Optional[str] = None):
    """Export the cluster task timeline as Chrome trace events
    (reference: ``ray timeline``). ``trace_id`` narrows the export to
    one distributed trace (its serve/engine spans included on a
    dedicated row). See ray_tpu/util/timeline.py."""
    from ray_tpu.util.timeline import timeline as _timeline
    return _timeline(filename, trace_id=trace_id)


def whereis(journal_file: Optional[str] = None, render: bool = True,
            task_path: bool = False):
    """Step-time attribution from the flight-recorder journal: folds
    the merged per-process journals into compute / comms / data-wait /
    pipeline-bubble / idle fractions per step and compares the measured
    bubble against the schedule's theoretical one. Reads the live
    journal store by default, or a ``flight_journal()`` dump when
    ``journal_file`` is given. Returns the report dict (and prints the
    rendered table unless ``render=False``).

    ``task_path=True`` switches to the submit-path phase budget: the
    sampled spec-build → result-return chains (core/task_phase.py)
    folded into a per-phase µs table with chain coverage."""
    from ray_tpu.devtools import whereis as _whereis
    journals = (_whereis._load_journals(journal_file)
                if journal_file else None)
    if task_path:
        report = _whereis.task_path_attribution(journals)
        if render:
            print(_whereis.render_task_path(report))
        return report
    report = _whereis.attribution(journals)
    if render:
        print(_whereis.render(report))
    return report


def flight_journal(filename: Optional[str] = None):
    """Dump the merged (clock-aligned) flight-recorder journals — the
    raw per-process event streams behind ``timeline()``/``whereis()``.
    Writes JSON when ``filename`` is given; returns the payload dict."""
    from ray_tpu.util import flight_recorder
    return flight_recorder.dump_journals(filename)


def profile_dump(filename: Optional[str] = None,
                 proc: Optional[str] = None) -> str:
    """Folded-text dump of the cluster-wide sampling profiler
    (``proc;role;frame;frame count`` per line — flamegraph.pl and
    speedscope both import it). Requires a run with RAY_TPU_PROFILER=1;
    ``proc`` narrows to one process label. Writes the text when
    ``filename`` is given; returns it either way. See
    ray_tpu/devtools/profiler.py."""
    from ray_tpu.devtools import profiler
    return profiler.dump(filename, proc=proc)
