"""Distributed FIFO queue backed by an async actor.

reference: python/ray/util/queue.py — same public API (`Queue` with
sync put/get, nowait and batch variants, `Empty`/`Full` mirroring
`queue`'s exceptions, `shutdown`). The implementation here rides
ray_tpu's async actors: the inner `_QueueActor` holds an
`asyncio.Queue`, so a blocked `get` coroutine yields the event loop
and never wedges concurrent `put`s (core/worker.py `_execute_async`).
"""
import asyncio
import queue as _stdlib_queue
from typing import Any, Dict, List, Optional

from ray_tpu import api
from ray_tpu.exceptions import TaskError

__all__ = ["Queue", "Empty", "Full"]


def _call(ref):
    """get() that surfaces the queue's own Full/Empty instead of the
    runtime's TaskError wrapper."""
    try:
        return api.get(ref)
    except TaskError as e:
        if isinstance(e.cause, (Full, Empty)):
            raise e.cause from None
        raise


class Empty(_stdlib_queue.Empty):
    pass


class Full(_stdlib_queue.Full):
    pass


class _QueueActor:
    """Holds the asyncio.Queue; every method is a coroutine so blocking
    ops interleave under max_concurrency."""

    def __init__(self, maxsize: int):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)

    async def qsize(self):
        return self.queue.qsize()

    async def empty(self):
        return self.queue.empty()

    async def full(self):
        return self.queue.full()

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self.queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full from None

    async def put_nowait(self, item):
        try:
            self.queue.put_nowait(item)
        except asyncio.QueueFull:
            raise Full from None

    async def put_nowait_batch(self, items: List[Any]):
        # Atomic: either the whole batch fits or nothing is enqueued.
        if self.queue.maxsize > 0 and \
                self.queue.qsize() + len(items) > self.queue.maxsize:
            raise Full(f"Cannot add {len(items)} items to queue of size "
                       f"{self.queue.qsize()} and maxsize "
                       f"{self.queue.maxsize}.")
        for item in items:
            self.queue.put_nowait(item)

    async def get(self, timeout: Optional[float] = None):
        try:
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty from None

    async def get_nowait(self):
        try:
            return self.queue.get_nowait()
        except asyncio.QueueEmpty:
            raise Empty from None

    async def get_nowait_batch(self, num_items: int):
        if num_items > self.queue.qsize():
            raise Empty(f"Cannot get {num_items} items from queue of "
                        f"size {self.queue.qsize()}.")
        return [self.queue.get_nowait() for _ in range(num_items)]


class Queue:
    """First-in-first-out queue shared between drivers/tasks/actors.

    Args:
        maxsize: maximum queue depth; 0 means unbounded.
        actor_options: `.options()` overrides for the backing actor
            (resources, name, placement).
    """

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[Dict] = None) -> None:
        actor_options = dict(actor_options or {})
        # Effectively unlimited interleaving (reference: asyncio queue
        # actor): every parked blocking put/get holds a concurrency
        # slot for its whole blocked duration, so a small cap would
        # deadlock once cap-many ops park — the drain call could never
        # acquire a slot.
        actor_options.setdefault("max_concurrency", 10_000)
        self.maxsize = maxsize
        self.actor = api.remote(_QueueActor) \
            .options(**actor_options).remote(maxsize)

    def __reduce__(self):
        deserializer = Queue._from_actor
        return deserializer, (self.actor, self.maxsize)

    @classmethod
    def _from_actor(cls, actor, maxsize):
        self = cls.__new__(cls)
        self.actor = actor
        self.maxsize = maxsize
        return self

    def qsize(self) -> int:
        return _call(self.actor.qsize.remote())

    def empty(self) -> bool:
        return _call(self.actor.empty.remote())

    def full(self) -> bool:
        return _call(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Add an item; blocks while full unless block=False."""
        if not block:
            _call(self.actor.put_nowait.remote(item))
            return
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        _call(self.actor.put.remote(item, timeout))

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        """Remove and return an item; blocks while empty unless
        block=False."""
        if not block:
            return _call(self.actor.get_nowait.remote())
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        return _call(self.actor.get.remote(timeout))

    def put_nowait(self, item: Any) -> None:
        return self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        """Atomically enqueue a batch (all or raise Full)."""
        if not isinstance(items, (list, tuple)):
            raise TypeError("put_nowait_batch expects a list of items")
        _call(self.actor.put_nowait_batch.remote(list(items)))

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        """Atomically dequeue num_items (or raise Empty)."""
        if not isinstance(num_items, int) or num_items < 0:
            raise ValueError("'num_items' must be a nonnegative integer")
        return _call(self.actor.get_nowait_batch.remote(num_items))

    def shutdown(self, force: bool = False,
                 grace_period_s: int = 5) -> None:
        """Terminate the backing actor; the queue is unusable after.

        force=False enqueues a barrier call and gives in-flight ops
        ``grace_period_s`` to drain before the kill (divergence: no
        per-actor graceful-exit primitive exists here, so ops blocked
        indefinitely — a put on a full queue nobody drains — still die
        with the actor after the grace window, matching the
        reference's fall-back-to-force behavior).
        """
        if self.actor is not None:
            if not force:
                try:
                    api.wait([self.actor.qsize.remote()],
                             timeout=grace_period_s)
                except Exception:  # graftlint: disable=GL004
                    pass  # actor already dying — proceed to the kill
            api.kill(self.actor, no_restart=True)
        self.actor = None
