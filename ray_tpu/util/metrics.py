"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:173,318,240 — metrics defined in
any task/actor/driver, aggregated centrally, exported in Prometheus
text format (the reference scrapes via the dashboard agent's
/metrics endpoint; here `prometheus_text()` renders the same exposition
format and the dashboard module serves it).

Workers report through the control-plane KV channel (one message per
update — fine for control-path metrics; hot-loop counters should
aggregate locally and flush periodically).
"""

from __future__ import annotations

import bisect
import threading

from ray_tpu.devtools import locktrace
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0]


class _Registry:
    """Process-global metric state (driver holds the authoritative
    copy; workers forward updates to it)."""

    def __init__(self):
        self.lock = locktrace.traced_lock("util.metrics")
        # (name, tag_items) -> value
        self.counters: Dict[Tuple, float] = {}
        self.gauges: Dict[Tuple, float] = {}
        # (name, tag_items) -> (boundaries, bucket counts, sum, count)
        self.histograms: Dict[Tuple, list] = {}
        self.descriptions: Dict[str, str] = {}

    def apply(self, kind: str, name: str, tags: Tuple, value: float,
              boundaries: Optional[Sequence[float]] = None) -> None:
        with self.lock:
            self._apply_locked(kind, name, tags, value, boundaries)

    def _apply_locked(self, kind: str, name: str, tags: Tuple,
                      value: float,
                      boundaries: Optional[Sequence[float]] = None) -> None:
        key = (name, tags)
        if kind == "counter":
            self.counters[key] = self.counters.get(key, 0.0) + value
        elif kind == "gauge":
            self.gauges[key] = value
        elif kind == "histogram":
            entry = self.histograms.get(key)
            if entry is None:
                bounds = list(boundaries or _DEFAULT_BOUNDARIES)
                entry = [bounds, [0] * (len(bounds) + 1), 0.0, 0]
                self.histograms[key] = entry
            bounds, buckets, _, _ = entry
            buckets[bisect.bisect_left(bounds, value)] += 1
            entry[2] += value
            entry[3] += 1

    def apply_batch(self, items) -> None:
        """Apply many updates under ONE lock acquisition — the flush
        path for hot-loop producers (e.g. the LLM engine stepper) that
        aggregate locally instead of paying a lock/RPC per update."""
        with self.lock:
            for kind, name, tags, value, boundaries in items:
                self._apply_locked(kind, name, tuple(tags), value,
                                   boundaries)

    def remove_series(self, name: str, tags: Tuple) -> None:
        """Drop one labeled series (a gauge whose subject — node,
        deployment — no longer exists must stop being exported, or
        scrapers chart zombie series forever). When the metric's last
        series goes, its description goes too — a dangling entry would
        keep exporting a header with no samples."""
        with self.lock:
            key = (name, tags)
            self.counters.pop(key, None)
            self.gauges.pop(key, None)
            self.histograms.pop(key, None)
            if not any(k[0] == name for table in (self.counters,
                                                  self.gauges,
                                                  self.histograms)
                       for k in table):
                self.descriptions.pop(name, None)


_registry = _Registry()


def remove_series(name: str, tags: Dict[str, str]) -> None:
    _registry.remove_series(name, tuple(sorted((tags or {}).items())))


def _record(kind: str, name: str, tags: Dict[str, str], value: float,
            boundaries=None) -> None:
    tag_items = tuple(sorted((tags or {}).items()))
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is not None and not getattr(rt, "is_driver", False):
        # worker: forward to the driver-held registry via the GCS channel
        rt.gcs_call("metrics_apply", kind, name, tag_items, value,
                    list(boundaries) if boundaries else None)
        return
    _registry.apply(kind, name, tag_items, value, boundaries)


def record_local(kind: str, name: str, tags: Dict[str, str], value: float,
                 boundaries=None) -> None:
    """Apply one update to THIS process's registry, never the
    worker->driver forwarding channel. For code running on an IO/event
    thread (the core IO loop): forwarding is a synchronous
    control-plane request whose reply only that same thread could
    dispatch — a self-deadlock."""
    _registry.apply(kind, name, tuple(sorted((tags or {}).items())),
                    value, boundaries)


def record_batch(items) -> None:
    """Apply a batch of metric updates in one shot. ``items``: iterable
    of ``(kind, name, tags_dict, value, boundaries)``. On a worker the
    whole batch rides ONE control-plane RPC instead of one per update —
    the flush path for hot loops that aggregate locally."""
    normalized = [
        (kind, name, tuple(sorted((tags or {}).items())), value,
         list(boundaries) if boundaries else None)
        for kind, name, tags, value, boundaries in items]
    if not normalized:
        return
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is not None and not getattr(rt, "is_driver", False):
        rt.gcs_call("metrics_apply_batch", normalized)
        return
    _registry.apply_batch(normalized)


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        # Under the registry lock: metrics are defined from arbitrary
        # threads (serve replicas, train workers) concurrently with
        # prometheus_text() reads. Don't let a later blank-description
        # re-registration of the same name clobber a real one.
        with _registry.lock:
            if description or name not in _registry.descriptions:
                _registry.descriptions[name] = description

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        return out


class Counter(Metric):
    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        _record("counter", self._name, self._tags(tags), value)

    def inc_local(self, value: float = 1.0,
                  tags: Optional[Dict[str, str]] = None) -> None:
        """Loop-thread-safe inc: applies to this process's registry
        with no worker->driver RPC (see record_local). Required on any
        rtpu-io-loop code path (graftlint GL010)."""
        record_local("counter", self._name, self._tags(tags), value)


class Gauge(Metric):
    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        _record("gauge", self._name, self._tags(tags), value)

    def set_local(self, value: float,
                  tags: Optional[Dict[str, str]] = None) -> None:
        """Loop-thread-safe set: no RPC (see record_local / GL010)."""
        record_local("gauge", self._name, self._tags(tags), value)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = list(boundaries or _DEFAULT_BOUNDARIES)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        _record("histogram", self._name, self._tags(tags), value,
                self._boundaries)

    def observe_local(self, value: float,
                      tags: Optional[Dict[str, str]] = None) -> None:
        """Loop-thread-safe observe: no RPC (see record_local /
        GL010)."""
        record_local("histogram", self._name, self._tags(tags), value,
                     self._boundaries)

    def percentile(self, q: float,
                   tags: Optional[Dict[str, str]] = None
                   ) -> Optional[float]:
        """Interpolated quantile (q in [0, 1]) of this histogram's
        labeled series, read straight from the registry — admission
        control and autoscaling policies use this instead of scraping
        the /metrics exposition text. Driver-side only: workers forward
        updates to the driver and hold no local counts. Returns None
        when the series has no observations."""
        return histogram_percentile(self._name, q, self._tags(tags))

    def snapshot(self, tags: Optional[Dict[str, str]] = None
                 ) -> Optional[tuple]:
        """(boundaries, bucket_counts, sum, count) copy of one labeled
        series, or None. Two snapshots' bucket-count difference feeds
        percentile_from_counts() for WINDOWED quantiles (lifetime
        histograms never forget a slow start; control loops need the
        recent distribution)."""
        return histogram_snapshot(self._name, self._tags(tags))


def histogram_snapshot(name: str, tags: Optional[Dict[str, str]] = None
                       ) -> Optional[tuple]:
    key = (name, tuple(sorted((tags or {}).items())))
    with _registry.lock:
        entry = _registry.histograms.get(key)
        if entry is None:
            return None
        bounds, buckets, total, count = entry
        return list(bounds), list(buckets), float(total), int(count)


def percentile_from_counts(bounds: Sequence[float],
                           buckets: Sequence[float],
                           q: float) -> Optional[float]:
    """Interpolated quantile from histogram bucket counts. ``buckets``
    has len(bounds)+1 entries (last = overflow). Linear interpolation
    inside the containing bucket; the unbounded overflow bucket reports
    the top boundary (the histogram can't resolve beyond it). Returns
    None — never raises — on an empty/all-zero snapshot or a series
    with no finite boundaries, so control loops (SLO autoscaler,
    whereis) can poll before traffic exists."""
    count = sum(buckets)
    if count <= 0 or not bounds:
        return None
    q = min(1.0, max(0.0, q))
    rank = q * count
    cumulative = 0.0
    for i, n in enumerate(buckets[:-1]):
        prev = cumulative
        cumulative += n
        if cumulative >= rank and n > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - prev) / n
            return lo + (hi - lo) * frac
    return float(bounds[-1])


def histogram_percentile(name: str, q: float,
                         tags: Optional[Dict[str, str]] = None
                         ) -> Optional[float]:
    snap = histogram_snapshot(name, tags)
    if snap is None:
        return None
    bounds, buckets, _total, _count = snap
    return percentile_from_counts(bounds, buckets, q)


def _esc_label(value) -> str:
    # Prometheus text-format label escaping: backslash, double-quote, and
    # newline must be escaped or scrapers reject the exposition.
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_tags(tags: Tuple, extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in tags]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _esc_help(text: str) -> str:
    # HELP text escaping per the exposition format: backslash + newline.
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def prometheus_text() -> str:
    """Prometheus exposition-format dump of every metric. ``# HELP`` /
    ``# TYPE`` headers are emitted once per metric family (not per
    labeled series — scrapers reject duplicate headers)."""
    reg = _registry
    lines: List[str] = []

    def header(name: str, kind: str) -> None:
        desc = reg.descriptions.get(name)
        if desc:
            lines.append(f"# HELP {name} {_esc_help(desc)}")
        lines.append(f"# TYPE {name} {kind}")

    with reg.lock:
        last = None
        for (name, tags), value in sorted(reg.counters.items()):
            if name != last:
                header(name, "counter")
                last = name
            lines.append(f"{name}{_fmt_tags(tags)} {value}")
        last = None
        for (name, tags), value in sorted(reg.gauges.items()):
            if name != last:
                header(name, "gauge")
                last = name
            lines.append(f"{name}{_fmt_tags(tags)} {value}")
        last = None
        for (name, tags), (bounds, buckets, total, count) in sorted(
                reg.histograms.items()):
            if name != last:
                header(name, "histogram")
                last = name
            cumulative = 0
            for bound, n in zip(bounds, buckets):
                cumulative += n
                le = 'le="%s"' % bound
                lines.append(f"{name}_bucket{_fmt_tags(tags, le)} "
                             f"{cumulative}")
            cumulative += buckets[-1]
            le_inf = 'le="+Inf"'
            lines.append(f"{name}_bucket{_fmt_tags(tags, le_inf)} "
                         f"{cumulative}")
            lines.append(f"{name}_sum{_fmt_tags(tags)} {total}")
            lines.append(f"{name}_count{_fmt_tags(tags)} {count}")
    return "\n".join(lines) + "\n"
