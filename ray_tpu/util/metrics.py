"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:173,318,240 — metrics defined in
any task/actor/driver, aggregated centrally, exported in Prometheus
text format (the reference scrapes via the dashboard agent's
/metrics endpoint; here `prometheus_text()` renders the same exposition
format and the dashboard module serves it).

Workers report through the control-plane KV channel (one message per
update — fine for control-path metrics; hot-loop counters should
aggregate locally and flush periodically).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0]


class _Registry:
    """Process-global metric state (driver holds the authoritative
    copy; workers forward updates to it)."""

    def __init__(self):
        self.lock = threading.Lock()
        # (name, tag_items) -> value
        self.counters: Dict[Tuple, float] = {}
        self.gauges: Dict[Tuple, float] = {}
        # (name, tag_items) -> (boundaries, bucket counts, sum, count)
        self.histograms: Dict[Tuple, list] = {}
        self.descriptions: Dict[str, str] = {}

    def apply(self, kind: str, name: str, tags: Tuple, value: float,
              boundaries: Optional[Sequence[float]] = None) -> None:
        with self.lock:
            key = (name, tags)
            if kind == "counter":
                self.counters[key] = self.counters.get(key, 0.0) + value
            elif kind == "gauge":
                self.gauges[key] = value
            elif kind == "histogram":
                entry = self.histograms.get(key)
                if entry is None:
                    bounds = list(boundaries or _DEFAULT_BOUNDARIES)
                    entry = [bounds, [0] * (len(bounds) + 1), 0.0, 0]
                    self.histograms[key] = entry
                bounds, buckets, _, _ = entry
                buckets[bisect.bisect_left(bounds, value)] += 1
                entry[2] += value
                entry[3] += 1


    def remove_series(self, name: str, tags: Tuple) -> None:
        """Drop one labeled series (a gauge whose subject — node,
        deployment — no longer exists must stop being exported, or
        scrapers chart zombie series forever)."""
        with self.lock:
            key = (name, tags)
            self.counters.pop(key, None)
            self.gauges.pop(key, None)
            self.histograms.pop(key, None)


_registry = _Registry()


def remove_series(name: str, tags: Dict[str, str]) -> None:
    _registry.remove_series(name, tuple(sorted((tags or {}).items())))


def _record(kind: str, name: str, tags: Dict[str, str], value: float,
            boundaries=None) -> None:
    tag_items = tuple(sorted((tags or {}).items()))
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is not None and not getattr(rt, "is_driver", False):
        # worker: forward to the driver-held registry via the GCS channel
        rt.gcs_call("metrics_apply", kind, name, tag_items, value,
                    list(boundaries) if boundaries else None)
        return
    _registry.apply(kind, name, tag_items, value, boundaries)


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        _registry.descriptions[name] = description

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        return out


class Counter(Metric):
    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        _record("counter", self._name, self._tags(tags), value)


class Gauge(Metric):
    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        _record("gauge", self._name, self._tags(tags), value)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = list(boundaries or _DEFAULT_BOUNDARIES)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        _record("histogram", self._name, self._tags(tags), value,
                self._boundaries)


def _esc_label(value) -> str:
    # Prometheus text-format label escaping: backslash, double-quote, and
    # newline must be escaped or scrapers reject the exposition.
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_tags(tags: Tuple, extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in tags]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text() -> str:
    """Prometheus exposition-format dump of every metric."""
    reg = _registry
    lines: List[str] = []
    with reg.lock:
        for (name, tags), value in sorted(reg.counters.items()):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_fmt_tags(tags)} {value}")
        for (name, tags), value in sorted(reg.gauges.items()):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_fmt_tags(tags)} {value}")
        for (name, tags), (bounds, buckets, total, count) in sorted(
                reg.histograms.items()):
            lines.append(f"# TYPE {name} histogram")
            cumulative = 0
            for bound, n in zip(bounds, buckets):
                cumulative += n
                lines.append(f"{name}_bucket"
                             f"{_fmt_tags(tags, f'le=\"{bound}\"')} "
                             f"{cumulative}")
            cumulative += buckets[-1]
            lines.append(f"{name}_bucket"
                         f"{_fmt_tags(tags, 'le=\"+Inf\"')} {cumulative}")
            lines.append(f"{name}_sum{_fmt_tags(tags)} {total}")
            lines.append(f"{name}_count{_fmt_tags(tags)} {count}")
    return "\n".join(lines) + "\n"
