"""Fixed-pool work distribution over actor handles.

Public surface matches ``ray.util.ActorPool`` (reference:
python/ray/util/actor_pool.py): ``map``, ``map_unordered``, ``submit``,
``get_next``, ``get_next_unordered``, ``has_next``, ``has_free``,
``pop_idle``, ``push``.

Internals are a ticket ledger, not the reference's parallel index maps:
every submission gets a monotonically increasing ticket; outstanding
work lives in one ``{ticket: (ref, actor)}`` dict and deferred
submissions in a backlog deque. Ordered consumption always yields the
lowest outstanding ticket, so interleaving ``get_next`` with
``get_next_unordered`` is well-defined here (the reference raises in
some of those interleavings).
"""
import collections
from typing import Any, Callable, Iterator, List, Optional, TypeVar

from ray_tpu import api
from ray_tpu.core.object_ref import ObjectRef

V = TypeVar("V")

__all__ = ["ActorPool"]


class _Ticket:
    __slots__ = ("ref", "actor")

    def __init__(self, ref: ObjectRef, actor: Any):
        self.ref = ref
        self.actor = actor


class ActorPool:
    """Keep a fixed set of actors saturated with submitted work.

    ``fn`` is called as ``fn(actor, value)`` and must return the
    ``ObjectRef`` of the dispatched actor call; the actor rejoins the
    idle set once that ref is consumed via ``get_next*``.
    """

    def __init__(self, actors: list):
        self._free: List[Any] = list(actors)
        self._ledger: "dict[int, _Ticket]" = {}
        self._backlog: "collections.deque" = collections.deque()
        self._ticket = 0

    # -- bulk maps ----------------------------------------------------
    def map(self, fn: Callable[[Any, V], ObjectRef],
            values: List[V]) -> Iterator[Any]:
        """Ordered iterator of fn results over values."""
        self._abandon_outstanding()
        for v in values:
            self.submit(fn, v)

        def _drain():
            while self.has_next():
                yield self.get_next()

        return _drain()

    def map_unordered(self, fn: Callable[[Any, V], ObjectRef],
                      values: List[V]) -> Iterator[Any]:
        """Completion-order iterator of fn results over values."""
        self._abandon_outstanding()
        for v in values:
            self.submit(fn, v)

        def _drain():
            while self.has_next():
                yield self.get_next_unordered()

        return _drain()

    def _abandon_outstanding(self) -> None:
        """Forget any half-consumed previous map.

        Results of in-flight tickets are discarded (never spliced into
        a newer map's output) but their actors must rejoin the idle set
        once the ledger is wiped — a 1-actor pool would otherwise starve
        forever. The backlog is dropped outright: those values belong to
        the abandoned map and were never dispatched.
        """
        stranded = [t.actor for t in self._ledger.values()]
        self._backlog.clear()
        self._ledger.clear()
        self._ticket = 0
        for actor in stranded:
            self._reclaim(actor)

    # -- incremental submission ---------------------------------------
    def submit(self, fn: Callable[[Any, V], ObjectRef], value: V) -> None:
        """Dispatch fn(actor, value) on an idle actor, or defer it."""
        if not self._free:
            self._backlog.append((fn, value))
            return
        actor = self._free.pop()
        self._ledger[self._ticket] = _Ticket(fn(actor, value), actor)
        self._ticket += 1

    def has_next(self) -> bool:
        return bool(self._ledger)

    def get_next(self, timeout: Optional[float] = None,
                 ignore_if_timedout: bool = False) -> Any:
        """Result of the earliest outstanding submission (blocking).

        On timeout raises ``TimeoutError``; with ``ignore_if_timedout``
        the hung submission is additionally discarded (actor reclaimed)
        so the caller can make progress past it.
        """
        if not self._ledger:
            raise StopIteration("ActorPool has no outstanding results")
        seq = min(self._ledger)
        entry = self._ledger[seq]
        if timeout is not None:
            ready, _ = api.wait([entry.ref], timeout=timeout)
            if not ready:
                if ignore_if_timedout:
                    self._retire(seq)
                    raise TimeoutError(
                        f"result of submission {seq} not ready within "
                        f"{timeout}s; the submission was discarded")
                raise TimeoutError(
                    f"result of submission {seq} not ready within "
                    f"{timeout}s")
        self._retire(seq)
        return api.get(entry.ref)

    def get_next_unordered(self, timeout: Optional[float] = None,
                           ignore_if_timedout: bool = False) -> Any:
        """Result of whichever outstanding submission finishes first."""
        if not self._ledger:
            raise StopIteration("ActorPool has no outstanding results")
        by_ref = {t.ref: seq for seq, t in self._ledger.items()}
        ready, _ = api.wait(list(by_ref), num_returns=1, timeout=timeout)
        if not ready:
            # No single submission to blame, so none is discarded even
            # under ignore_if_timedout.
            raise TimeoutError(
                f"no result ready within {timeout}s")
        seq = by_ref[ready[0]]
        ref = self._ledger[seq].ref
        self._retire(seq)
        return api.get(ref)

    def _retire(self, seq: int) -> None:
        entry = self._ledger.pop(seq)
        self._reclaim(entry.actor)

    def _reclaim(self, actor: Any) -> None:
        """Return an actor to the idle set, then pump the backlog."""
        self._free.append(actor)
        while self._backlog and self._free:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    # -- pool membership ----------------------------------------------
    def has_free(self) -> bool:
        """True iff an actor is idle and the backlog is empty."""
        return bool(self._free) and not self._backlog

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all busy)."""
        if not self.has_free():
            return None
        return self._free.pop()

    def push(self, actor: Any) -> None:
        """Add an actor to the pool."""
        if actor in self._free or any(
                t.actor is actor for t in self._ledger.values()):
            raise ValueError("actor is already a member of this pool")
        self._reclaim(actor)
