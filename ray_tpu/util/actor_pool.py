"""Fixed-pool work distribution over actor handles.

reference: python/ray/util/actor_pool.py — same public API
(`map`, `map_unordered`, `submit`, `get_next`, `get_next_unordered`,
`has_next`, `has_free`, `pop_idle`, `push`); independent
implementation over ray_tpu's wait/get primitives.
"""
from typing import Any, Callable, Iterator, List, Optional, TypeVar

from ray_tpu import api
from ray_tpu.core.object_ref import ObjectRef

V = TypeVar("V")

__all__ = ["ActorPool"]


class ActorPool:
    """Operate on a fixed pool of actors, keeping every actor busy.

    ``fn`` receives ``(actor, value)`` and must return the ObjectRef of
    the submitted call; the actor is considered busy until that ref
    resolves.
    """

    def __init__(self, actors: list):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor: dict = {}     # ref -> (index, actor)
        self._index_to_future: dict = {}     # submit index -> ref
        self._next_task_index = 0            # next index to hand out
        self._next_return_index = 0          # next index get_next returns
        self._pending_submits: list = []     # (fn, value) waiting for an actor

    # -- bulk maps ----------------------------------------------------
    def map(self, fn: Callable[[Any, V], ObjectRef],
            values: List[V]) -> Iterator[Any]:
        """Ordered iterator of fn results over values."""
        # Defensive reset mirroring the reference: a half-consumed
        # previous map must not leak its unreturned futures into ours.
        self._reset_return_state()
        for v in values:
            self.submit(fn, v)

        def result_iterator():
            while self.has_next():
                yield self.get_next()

        return result_iterator()

    def map_unordered(self, fn: Callable[[Any, V], ObjectRef],
                      values: List[V]) -> Iterator[Any]:
        """Completion-order iterator of fn results over values."""
        self._reset_return_state()
        for v in values:
            self.submit(fn, v)

        def result_iterator():
            while self.has_next():
                yield self.get_next_unordered()

        return result_iterator()

    def _reset_return_state(self) -> None:
        # Drain (not just clear): actors still busy with an abandoned
        # map's tasks must come back to the pool, or they leak and a
        # 1-actor pool would silently yield zero results forever. The
        # abandoned map's not-yet-submitted values are dropped too —
        # pumping them would splice stale results into the NEW map's
        # output. Clear all state before handing actors back because
        # _return_actor pumps _pending_submits.
        busy = [actor for _, actor in self._future_to_actor.values()]
        self._pending_submits.clear()
        self._future_to_actor.clear()
        self._index_to_future.clear()
        self._next_task_index = 0
        self._next_return_index = 0
        for actor in busy:
            self._return_actor(actor)

    # -- incremental submission ---------------------------------------
    def submit(self, fn: Callable[[Any, V], ObjectRef], value: V) -> None:
        """Run fn(actor, value) on an idle actor, or queue it."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: Optional[float] = None,
                 ignore_if_timedout: bool = False) -> Any:
        """Next result in submission order (blocks on that one task)."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        if self._next_return_index >= self._next_task_index:
            raise ValueError("It is not allowed to call get_next() after "
                             "get_next_unordered().")
        future = self._index_to_future[self._next_return_index]
        timeout_msg = "Timed out waiting for result"
        raise_timeout_after_ignore = False
        if timeout is not None:
            done, _ = api.wait([future], timeout=timeout)
            if not done:
                if not ignore_if_timedout:
                    raise TimeoutError(timeout_msg)
                raise_timeout_after_ignore = True
        # On an ignored timeout the task is skipped, not retained: drop
        # its future, free the actor, and advance — otherwise the caller
        # can never get past a hung task.
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        if raise_timeout_after_ignore:
            raise TimeoutError(timeout_msg + ". The task has been "
                               "ignored.")
        return api.get(future)

    def get_next_unordered(self, timeout: Optional[float] = None,
                           ignore_if_timedout: bool = False) -> Any:
        """Earliest-finished result regardless of submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        done, _ = api.wait(list(self._future_to_actor), num_returns=1,
                           timeout=timeout)
        if done:
            future = done[0]
            i, actor = self._future_to_actor.pop(future)
            self._return_actor(actor)
            del self._index_to_future[i]
            self._next_return_index = max(self._next_return_index, i + 1)
            return api.get(future)
        # unordered: no specific task to skip — nothing to ignore
        raise TimeoutError("Timed out waiting for result")

    def _return_actor(self, actor: Any) -> None:
        self._idle_actors.append(actor)
        while self._pending_submits and self._idle_actors:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    # -- pool membership ----------------------------------------------
    def has_free(self) -> bool:
        """True iff an actor is idle and nothing is queued."""
        return bool(self._idle_actors) and not self._pending_submits

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all busy)."""
        if self.has_free():
            return self._idle_actors.pop()
        return None

    def push(self, actor: Any) -> None:
        """Add an actor to the pool."""
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle_actors or actor in busy:
            raise ValueError("Actor already belongs to current ActorPool")
        self._return_actor(actor)
