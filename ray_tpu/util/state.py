"""State API: programmatic cluster introspection.

Reference: python/ray/util/state/api.py (list_tasks/list_actors/
list_nodes/list_objects/list_placement_groups, summarize_tasks) backed
by the GCS task-event store (gcs_task_manager.h:97) and the dashboard
state aggregator. Here the control plane lives in the driver process,
so the API reads the live runtime directly; `ray_tpu.scripts.cli`
serves the same data out-of-process from the session state dump.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional


def _runtime():
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    if rt is None or not getattr(rt, "is_driver", False):
        raise RuntimeError("state API requires an initialized driver "
                           "(call ray_tpu.init first)")
    return rt


def list_tasks(limit: int = 1000,
               filters: Optional[Dict[str, Any]] = None) -> List[dict]:
    """Latest known state per task, newest first."""
    rt = _runtime()
    latest: Dict[str, dict] = {}
    for ev in rt.gcs.list_task_events(limit=100_000):
        latest[ev.task_id.hex()] = {
            "task_id": ev.task_id.hex(),
            "name": ev.name,
            "state": ev.state,
            "node_id": ev.node_id.hex() if ev.node_id else None,
            "error": ev.error,
            "timestamp": ev.timestamp,
            "trace_id": ev.trace_id,
        }
    rows = sorted(latest.values(), key=lambda r: -r["timestamp"])
    if filters:
        rows = [r for r in rows
                if all(r.get(k) == v for k, v in filters.items())]
    return rows[:limit]


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_tasks(limit=10**9):
        counts[row["state"]] = counts.get(row["state"], 0) + 1
    return counts


def list_actors(limit: int = 1000) -> List[dict]:
    rt = _runtime()
    with rt.gcs.lock:
        records = list(rt.gcs.actors.values())
    return [{
        "actor_id": r.actor_id.hex(),
        "class_name": ((r.spec.name if r.spec else "") or "").split(".")[0],
        "state": r.state,
        "name": r.name,
        "restarts": r.num_restarts,
    } for r in records[:limit]]


def list_nodes() -> List[dict]:
    rt = _runtime()
    snap = rt.scheduler.snapshot()
    out = []
    for record in rt.gcs.alive_nodes():
        res = snap.get(record.node_id)
        out.append({
            "node_id": record.node_id.hex(),
            "alive": record.alive,
            "resources_total": dict(record.resources_total),
            "resources_available": dict(res.available) if res else {},
            "labels": dict(record.labels),
            "is_head": record.node_id == rt.head_node_id,
        })
    return out


def list_objects(limit: int = 1000) -> List[dict]:
    rt = _runtime()
    with rt.reference_counter._lock:
        counts = dict(rt.reference_counter._counts)
    out = []
    for oid, count in list(counts.items())[:limit]:
        loc = rt.task_manager.get_location(oid)
        out.append({
            "object_id": oid.hex(),
            "reference_count": count,
            "location": (loc.kind if loc else None),
            "node_id": (loc.node_id.hex()
                        if loc and loc.node_id else None),
        })
    return out


def list_placement_groups() -> List[dict]:
    rt = _runtime()
    with rt.gcs.lock:
        records = list(rt.gcs.placement_groups.values())
    return [{
        "placement_group_id": r.pg_id.hex(),
        "name": r.name,
        "state": r.state,
        "strategy": r.strategy,
        "bundles": [{"index": b.index, "resources": dict(b.resources),
                     "node_id": b.node_id.hex() if b.node_id else None}
                    for b in r.bundles],
    } for r in records]


def list_cluster_events(limit: int = 1000,
                        kinds: Optional[List[str]] = None,
                        severity: Optional[str] = None,
                        node_id: Optional[str] = None,
                        worker_id: Optional[str] = None,
                        actor_id: Optional[str] = None,
                        task_id: Optional[str] = None,
                        since_seq: Optional[int] = None) -> List[dict]:
    """Cluster lifecycle events (core/events.py), chronological.
    ``kinds`` filters to an iterable of kind names; ``severity`` is a
    MINIMUM level ("WARNING" keeps WARNING+ERROR); entity filters match
    hex-string ids; ``since_seq`` keeps events newer than a seq (the
    --follow cursor). Reference: ``ray list cluster-events``."""
    rt = _runtime()
    return [ev.to_dict() for ev in rt.gcs.list_cluster_events(
        limit=limit, kinds=kinds, severity=severity, node_id=node_id,
        worker_id=worker_id, actor_id=actor_id, task_id=task_id,
        since_seq=since_seq)]


def list_jobs() -> List[dict]:
    rt = _runtime()
    with rt.gcs.lock:
        records = list(rt.gcs.jobs.values())
    out = [{
        "job_id": r.job_id.hex(),
        "type": "driver",
        "state": r.state,
        "start_time": r.start_time,
        "end_time": r.end_time,
    } for r in records]
    # Submitted jobs (JobSubmissionClient) live in the GCS "jobs" KV
    # namespace — the same records every submission client sees.
    from ray_tpu.job_submission import list_job_infos
    for info in list_job_infos(rt.gcs):
        out.append({
            "job_id": info.get("submission_id"),
            "type": "submission",
            "state": info.get("status"),
            "start_time": info.get("start_time"),
            "end_time": info.get("end_time"),
            "entrypoint": info.get("entrypoint"),
        })
    return out


# ---------------------------------------------------------------------------
# Timeline (reference: `ray timeline` → Chrome trace from task events)
# ---------------------------------------------------------------------------

def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace events from the task-event store; optionally write
    to `filename` (load in chrome://tracing or Perfetto)."""
    rt = _runtime()
    by_task: Dict[str, List] = {}
    for ev in rt.gcs.list_task_events(limit=1_000_000):
        by_task.setdefault(ev.task_id.hex(), []).append(ev)
    trace: List[dict] = []
    for tid, events in by_task.items():
        events.sort(key=lambda e: e.timestamp)
        start = next((e for e in events
                      if e.state in ("SCHEDULED", "RUNNING")), events[0])
        end = next((e for e in reversed(events)
                    if e.state in ("FINISHED", "FAILED")), None)
        node = next((e.node_id.hex()[:8] for e in events if e.node_id),
                    "pending")
        # In-flight tasks become open spans clipped at now — a hung or
        # leaked task must be visible in the trace, not silently absent.
        end_state = end.state if end is not None else "RUNNING"
        end_ts = end.timestamp if end is not None else time.time()
        trace.append({
            "name": events[0].name,
            "cat": "task",
            "ph": "X",
            "ts": start.timestamp * 1e6,
            "dur": max((end_ts - start.timestamp) * 1e6, 1.0),
            "pid": node,
            "tid": tid[:8],
            "args": {"state": end_state, "task_id": tid},
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


# ---------------------------------------------------------------------------
# Session state dump — feeds the out-of-process CLI
# ---------------------------------------------------------------------------

def state_snapshot() -> dict:
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is None or not getattr(rt, "is_driver", False):
        # No driver in this process: degrade to a partial snapshot
        # instead of raising out of every caller (the CLI and dashboard
        # render the empty tables).
        return {
            "timestamp": time.time(),
            "driver": False,
            "dashboard_url": None,
            "nodes": [], "actors": [], "tasks": [],
            "task_summary": {}, "placement_groups": [], "jobs": [],
            "events": [],
            "resources_total": {}, "resources_available": {},
        }
    return {
        "timestamp": time.time(),
        "driver": True,
        "dashboard_url": getattr(rt, "dashboard_url", None),
        "nodes": list_nodes(),
        "actors": list_actors(),
        "tasks": list_tasks(limit=200),
        "task_summary": summarize_tasks(),
        "placement_groups": list_placement_groups(),
        "jobs": list_jobs(),
        "events": list_cluster_events(limit=500),
        "resources_total": _totals("resources_total"),
        "resources_available": _totals("resources_available"),
    }


def _totals(key: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for node in list_nodes():
        for k, v in node[key].items():
            out[k] = out.get(k, 0.0) + v
    return out
