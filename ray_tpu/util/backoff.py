"""Capped exponential backoff with jitter — the one retry clock.

Every reconnect/retry loop in the tree (client reconnect, node-daemon
reconnect, collective KV waits, object pulls, flight/refsan flushers)
shares this helper so a 128-node cluster does not thundering-herd the
head after a drill: jitter decorrelates the retry storms that a fleet
of identical timers would otherwise synchronize (reference: AWS
architecture blog "Exponential Backoff And Jitter"; the reference's
retryable_grpc_client.h exposes the same base/max knobs).

Two surfaces:

* :class:`Backoff` — stateful; ``wait()`` sleeps the next jittered
  delay (interruptible via an Event, bounded by an optional deadline)
  and returns False once retrying should stop.
* :func:`jittered` — stateless one-shot: jitter a single delay value
  (for loops that manage their own schedule).

graftlint GL019 (``UnboundedRetry``) flags retry loops that use
neither this module nor an explicit sleep/deadline.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


def jittered(delay: float, jitter: float = 0.5,
             rng: Optional[random.Random] = None) -> float:
    """Equal-jitter a delay: keep ``(1-jitter)`` of it deterministic
    and randomize the rest, so retries stay near the intended cadence
    but a fleet of peers decorrelates. ``jitter=0`` is a no-op."""
    if jitter <= 0.0 or delay <= 0.0:
        return delay
    jitter = min(jitter, 1.0)
    r = (rng or _rng).random()
    return delay * (1.0 - jitter) + delay * jitter * r


_rng = random.Random()


class Backoff:
    """Capped exponential backoff with equal jitter.

    ``initial_s`` doubles (``multiplier``) up to ``max_s``; each
    ``wait()`` sleeps the next jittered delay. With ``deadline_s`` set,
    ``wait()`` returns False (without sleeping past it) once the
    deadline is reached — the caller's signal to stop retrying. An
    optional Event interrupts the sleep (shutdown paths); a set event
    also returns False.

    Not thread-safe: one Backoff per retry loop.
    """

    def __init__(self, initial_s: float = 0.05, max_s: float = 2.0,
                 multiplier: float = 2.0, jitter: float = 0.5,
                 deadline_s: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        self.initial_s = initial_s
        self.max_s = max_s
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = rng or _rng
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)
        self._delay = initial_s
        self.attempts = 0

    def reset(self) -> None:
        """Back to the initial delay (e.g. after a successful call)."""
        self._delay = self.initial_s
        self.attempts = 0

    def remaining(self) -> Optional[float]:
        """Seconds until the deadline; None when unbounded."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def expired(self) -> bool:
        remaining = self.remaining()
        return remaining is not None and remaining <= 0.0

    def next_delay(self) -> float:
        """Advance the schedule and return the next jittered delay
        (without sleeping). Clamped to the deadline when one is set."""
        delay = jittered(self._delay, self.jitter, self._rng)
        self._delay = min(self._delay * self.multiplier, self.max_s)
        self.attempts += 1
        remaining = self.remaining()
        if remaining is not None:
            delay = min(delay, remaining)
        return max(0.0, delay)

    def wait(self, event: Optional[threading.Event] = None) -> bool:
        """Sleep the next jittered delay. Returns False when retrying
        should stop: the deadline passed, or ``event`` was set while
        waiting (or before)."""
        if self.expired():
            return False
        if event is not None and event.is_set():
            return False
        delay = self.next_delay()
        if event is not None:
            if event.wait(delay):
                return False
        elif delay > 0.0:
            time.sleep(delay)
        return not self.expired()
