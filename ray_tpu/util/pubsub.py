"""Cluster-wide pubsub usable from the driver AND from workers.

Capability parity with the reference's pubsub clients (reference:
src/ray/pubsub/publisher.h:245 / subscriber.h:215 and the Python GCS
subscriber, python_gcs_subscriber.cc). The publisher lives in the head;
worker subscriptions register a push route over the worker's node
socket (and the daemon's control connection for remote hosts).

    from ray_tpu.util import pubsub
    pubsub.subscribe("my-channel", lambda msg: ...)
    pubsub.publish("my-channel", {"anything": "picklable"})
"""

from __future__ import annotations

from typing import Any, Callable


def subscribe(channel: str, callback: Callable[[Any], None]) -> None:
    """Invoke ``callback(message)`` for every publish on ``channel``.
    Callbacks run on a runtime thread — keep them fast and non-blocking.
    """
    from ray_tpu.core import runtime as runtime_mod
    runtime_mod.get_runtime().subscribe_channel(channel, callback)


def publish(channel: str, message: Any) -> None:
    """Publish a picklable message to every subscriber, cluster-wide."""
    from ray_tpu.core import runtime as runtime_mod
    runtime_mod.get_runtime().publish_channel(channel, message)
