"""Timeline export: task events → Chrome trace-event JSON.

Capability parity with the reference's ``ray timeline``
(reference: python/ray/_private/state.py chrome_tracing_dump — task
events from the GCS task-event store rendered in the Chrome
trace-event format, viewable at chrome://tracing or Perfetto).

Tracks: one process row per node, one thread row per worker. Each
executed task is a complete slice (worker-measured start/duration);
user ``profile()`` spans nest on the same track; parent→child task
submissions are drawn as flow arrows.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def chrome_trace_events(runtime=None) -> List[Dict[str, Any]]:
    """Build the Chrome trace-event list from the live GCS store."""
    if runtime is None:
        from ray_tpu.core import runtime as runtime_mod
        runtime = runtime_mod.get_runtime()
    events = runtime.gcs.list_task_events(limit=1_000_000)
    out: List[Dict[str, Any]] = []
    # task hex → (RUNNING ts_us, pid, tid) for flow-arrow endpoints
    slices: Dict[str, tuple] = {}
    flow_id = 0

    def track(ev):
        pid = f"node:{ev.node_id.hex()[:8]}" if ev.node_id else "node:?"
        tid = (f"worker:{ev.worker_id.hex()[:8]}"
               if ev.worker_id else "scheduler")
        return pid, tid

    # first pass: index every task's execution slice — a child often
    # finishes (and thus records its RUNNING event) before its waiting
    # parent does, so flows can't be matched in arrival order
    for ev in events:
        if ev.state == "RUNNING" and ev.duration is not None:
            slices[ev.task_id.hex()] = (ev.timestamp * 1e6, *track(ev))

    for ev in events:
        pid, tid = track(ev)
        ts_us = ev.timestamp * 1e6
        if ev.state == "RUNNING" and ev.duration is not None:
            out.append({
                "name": ev.name, "cat": "task", "ph": "X",
                "ts": ts_us, "dur": ev.duration * 1e6,
                "pid": pid, "tid": tid,
                "args": {"task_id": ev.task_id.hex()},
            })
            if ev.parent_task_id is not None:
                parent = slices.get(ev.parent_task_id.hex())
                if parent is not None:
                    flow_id += 1
                    p_ts, p_pid, p_tid = parent
                    out.append({"name": "submit", "cat": "flow",
                                "ph": "s", "id": flow_id,
                                "ts": max(p_ts, ts_us - 1),
                                "pid": p_pid, "tid": p_tid})
                    out.append({"name": "submit", "cat": "flow",
                                "ph": "f", "bp": "e", "id": flow_id,
                                "ts": ts_us, "pid": pid, "tid": tid})
        elif ev.state == "PROFILE" and ev.duration is not None:
            out.append({
                "name": ev.name, "cat": "profile", "ph": "X",
                "ts": ts_us, "dur": ev.duration * 1e6,
                "pid": pid, "tid": tid,
                "args": {"task_id": ev.task_id.hex()},
            })
        elif ev.state == "FAILED":
            out.append({
                "name": f"FAILED:{ev.name}", "cat": "task", "ph": "i",
                "ts": ts_us, "pid": pid, "tid": tid, "s": "t",
                "args": {"error": (ev.error or "")[:500]},
            })
    return out


def timeline(filename: Optional[str] = None, runtime=None):
    """Export the cluster timeline. Returns the event list, and writes
    Chrome trace JSON to ``filename`` when given (open in
    chrome://tracing or https://ui.perfetto.dev)."""
    events = chrome_trace_events(runtime)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
