"""Timeline export: task events → Chrome trace-event JSON.

Capability parity with the reference's ``ray timeline``
(reference: python/ray/_private/state.py chrome_tracing_dump — task
events from the GCS task-event store rendered in the Chrome
trace-event format, viewable at chrome://tracing or Perfetto).

Tracks: one process row per node, one thread row per worker. Each
executed task is a complete slice (worker-measured start/duration);
user ``profile()`` spans nest on the same track; parent→child task
submissions are drawn as flow arrows.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def chrome_trace_events(runtime=None,
                        trace_id: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Build the Chrome trace-event list from the live GCS store.

    ``trace_id`` switches to the trace-grouped view: only that trace's
    task slices are kept, and the distributed spans recorded for it
    (serve proxy/router/replica hops, engine spans, user
    ``tracing.span()`` blocks) render as an extra ``trace:<id>`` row —
    one request's whole journey on one screen."""
    if runtime is None:
        from ray_tpu.core import runtime as runtime_mod
        runtime = runtime_mod.get_runtime()
    events = runtime.gcs.list_task_events(limit=1_000_000)
    if trace_id is not None:
        events = [ev for ev in events if ev.trace_id == trace_id]
    out: List[Dict[str, Any]] = []
    if trace_id is not None:
        row = f"trace:{trace_id[:8]}"
        for (_tid, span_id, _parent, name, component, t_start,
             duration, tags) in runtime.gcs.spans_for_trace(trace_id):
            out.append({
                "name": name, "cat": "span", "ph": "X",
                "ts": t_start * 1e6, "dur": duration * 1e6,
                "pid": row, "tid": component,
                "args": {"span_id": span_id, **(tags or {})},
            })
    # task hex → (RUNNING ts_us, pid, tid) for flow-arrow endpoints
    slices: Dict[str, tuple] = {}
    flow_id = 0

    def track(ev):
        pid = f"node:{ev.node_id.hex()[:8]}" if ev.node_id else "node:?"
        tid = (f"worker:{ev.worker_id.hex()[:8]}"
               if ev.worker_id else "scheduler")
        return pid, tid

    # first pass: index every task's execution slice — a child often
    # finishes (and thus records its RUNNING event) before its waiting
    # parent does, so flows can't be matched in arrival order
    for ev in events:
        if ev.state == "RUNNING" and ev.duration is not None:
            slices[ev.task_id.hex()] = (ev.timestamp * 1e6, *track(ev))

    for ev in events:
        pid, tid = track(ev)
        ts_us = ev.timestamp * 1e6
        if ev.state == "RUNNING" and ev.duration is not None:
            args = {"task_id": ev.task_id.hex()}
            if ev.trace_id is not None:
                args["trace_id"] = ev.trace_id
            out.append({
                "name": ev.name, "cat": "task", "ph": "X",
                "ts": ts_us, "dur": ev.duration * 1e6,
                "pid": pid, "tid": tid,
                "args": args,
            })
            if ev.parent_task_id is not None:
                parent = slices.get(ev.parent_task_id.hex())
                if parent is not None:
                    flow_id += 1
                    p_ts, p_pid, p_tid = parent
                    out.append({"name": "submit", "cat": "flow",
                                "ph": "s", "id": flow_id,
                                "ts": max(p_ts, ts_us - 1),
                                "pid": p_pid, "tid": p_tid})
                    out.append({"name": "submit", "cat": "flow",
                                "ph": "f", "bp": "e", "id": flow_id,
                                "ts": ts_us, "pid": pid, "tid": tid})
        elif ev.state == "PROFILE" and ev.duration is not None:
            out.append({
                "name": ev.name, "cat": "profile", "ph": "X",
                "ts": ts_us, "dur": ev.duration * 1e6,
                "pid": pid, "tid": tid,
                "args": {"task_id": ev.task_id.hex()},
            })
        elif ev.state == "FAILED":
            out.append({
                "name": f"FAILED:{ev.name}", "cat": "task", "ph": "i",
                "ts": ts_us, "pid": pid, "tid": tid, "s": "t",
                "args": {"error": (ev.error or "")[:500]},
            })
    # Flight-recorder journals (when recording): clock-aligned
    # per-process tracks merged into the same export — IO-loop
    # dispatch, pipeline instructions, shuffle waves, prefetch waits,
    # collective hops, serve engine steps.
    from ray_tpu.util import flight_recorder
    flight = flight_recorder.chrome_events()
    if trace_id is not None:
        flight = [ev for ev in flight
                  if ev.get("args", {}).get("trace_id") == trace_id]
    out.extend(flight)
    return out


def timeline(filename: Optional[str] = None, runtime=None,
             trace_id: Optional[str] = None):
    """Export the cluster timeline. Returns the event list, and writes
    Chrome trace JSON to ``filename`` when given (open in
    chrome://tracing or https://ui.perfetto.dev). ``trace_id`` narrows
    the export to one distributed trace, with its serve/engine spans on
    a dedicated trace row."""
    events = chrome_trace_events(runtime, trace_id=trace_id)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def speedscope_profile(filename: Optional[str] = None,
                       profiles: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """Render the sampling profiler's collapsed stacks
    (devtools/profiler.py) in the speedscope file format — one sampled
    profile per process, frames shared — loadable at
    https://www.speedscope.app (File → Import) or via ``speedscope
    file.json``. ``profiles`` defaults to the live merged store."""
    if profiles is None:
        from ray_tpu.devtools import profiler
        profiles = profiler.merged_profiles()
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []

    def _frame(name: str) -> int:
        idx = frame_index.get(name)
        if idx is None:
            idx = frame_index[name] = len(frames)
            frames.append({"name": name})
        return idx

    rendered = []
    for label in sorted(profiles):
        snap = profiles[label]
        samples: List[List[int]] = []
        weights: List[int] = []
        for stack, n in sorted(snap.get("counts", {}).items()):
            samples.append([_frame(part)
                            for part in stack.split(";") if part])
            weights.append(int(n))
        rendered.append({
            "type": "sampled",
            "name": label,
            "unit": "none",        # weights are sample counts
            "startValue": 0,
            "endValue": sum(weights),
            "samples": samples,
            "weights": weights,
        })
    payload = {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": rendered,
        "name": "ray_tpu profile",
        "exporter": "ray_tpu",
    }
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
    return payload
