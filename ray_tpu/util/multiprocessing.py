"""Drop-in ``multiprocessing.Pool`` over the cluster.

reference: python/ray/util/multiprocessing/pool.py — same public
surface (`Pool` with apply/apply_async/map/map_async/starmap/
imap/imap_unordered/close/terminate/join, `AsyncResult`), built here
as a thin layer over worker actors (batches round-robin across them)
so ``initializer`` runs once per worker exactly like a forked process
pool.
"""
import itertools
from typing import Any, Callable, Iterable, List, Optional, Tuple

from ray_tpu import api

__all__ = ["Pool", "AsyncResult", "TimeoutError"]

TimeoutError = TimeoutError  # re-export for multiprocessing API parity


class _PoolWorker:
    """One pool slot; runs the initializer at construction like a
    freshly forked worker process."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, func: Callable, batch: List[Any], star: bool):
        out = []
        for item in batch:
            out.append(func(*item) if star else func(item))
        return out


class AsyncResult:
    """Handle for an in-flight map/apply (multiprocessing.AsyncResult
    semantics: get/wait/ready/successful)."""

    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def ready(self) -> bool:
        done, _ = api.wait(list(self._refs),
                           num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)

    def wait(self, timeout: Optional[float] = None) -> None:
        api.wait(list(self._refs), num_returns=len(self._refs),
                 timeout=timeout)

    def get(self, timeout: Optional[float] = None) -> Any:
        done, not_done = api.wait(
            list(self._refs), num_returns=len(self._refs),
            timeout=timeout)
        if not_done:
            raise TimeoutError("Result not ready")
        batches = api.get(list(self._refs))
        flat = [x for b in batches for x in b]
        return flat[0] if self._single else flat

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("Result is not ready")
        try:
            self.get()
            return True
        except Exception:
            return False


class Pool:
    """Actor-backed process-pool equivalent.

    ``processes`` defaults to the cluster's total CPU count. Each
    worker is an actor, so ``initializer(*initargs)`` runs once per
    worker and module-level state persists across tasks on the same
    worker — matching forked-pool semantics.
    """

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: Tuple = (),
                 maxtasksperchild: Optional[int] = None,
                 actor_options: Optional[dict] = None):
        if processes is None:
            processes = max(1, int(api.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        cls = api.remote(_PoolWorker)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actors = [cls.remote(initializer, tuple(initargs))
                        for _ in range(processes)]
        self._closed = False
        self._inflight: List[Any] = []  # refs join() must drain

    # -- helpers ------------------------------------------------------
    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunk(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            # multiprocessing heuristic: ~4 waves across the pool
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _submit_batches(self, func, batches, star) -> List[Any]:
        # Round-robin over the actors directly (ordered refs, no
        # shared scheduling state) so concurrent maps don't interleave.
        refs = []
        for actor, batch in zip(itertools.cycle(self._actors), batches):
            refs.append(actor.run_batch.remote(func, batch, star))
        # Track for join(); prune what has already finished so a
        # long-lived pool doesn't pin every result it ever produced.
        if self._inflight:
            done, _ = api.wait(self._inflight,
                               num_returns=len(self._inflight),
                               timeout=0)
            done_set = set(done)
            self._inflight = [r for r in self._inflight
                              if r not in done_set]
        self._inflight.extend(refs)
        return refs

    # -- apply --------------------------------------------------------
    def apply(self, func: Callable, args: Tuple = (), kwds: dict = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: Tuple = (),
                    kwds: dict = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_running()
        kwds = kwds or {}
        # run_batch passes the (placeholder) item as arg 1 — absorb it
        call = (lambda _item, f=func, a=tuple(args), k=dict(kwds):
                f(*a, **k))
        refs = self._submit_batches(call, [[None]], star=False)
        res = AsyncResult(refs, single=True)
        _fire_callbacks(res, callback, error_callback)
        return res

    # -- map / starmap ------------------------------------------------
    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_running()
        batches, _ = self._chunk(iterable, chunksize)
        res = AsyncResult(self._submit_batches(func, batches, star=False))
        _fire_callbacks(res, callback, error_callback)
        return res

    def starmap(self, func: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func: Callable, iterable: Iterable,
                      chunksize: Optional[int] = None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_running()
        batches, _ = self._chunk(iterable, chunksize)
        res = AsyncResult(self._submit_batches(func, batches, star=True))
        _fire_callbacks(res, callback, error_callback)
        return res

    # -- imap ---------------------------------------------------------
    def imap(self, func: Callable, iterable: Iterable,
             chunksize: int = 1):
        """Ordered lazy iterator (results stream as chunks finish)."""
        self._check_running()
        batches, _ = self._chunk(iterable, chunksize)
        refs = self._submit_batches(func, batches, star=False)
        for ref in refs:
            for item in api.get(ref):
                yield item

    def imap_unordered(self, func: Callable, iterable: Iterable,
                       chunksize: int = 1):
        """Completion-order lazy iterator."""
        self._check_running()
        batches, _ = self._chunk(iterable, chunksize)
        pending = self._submit_batches(func, batches, star=False)
        while pending:
            done, pending = api.wait(pending, num_returns=1)
            for item in api.get(done[0]):
                yield item

    # -- lifecycle ----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            api.kill(a, no_restart=True)
        self._actors = []
        self._inflight = []  # killed actors won't deliver these

    def join(self) -> None:
        """Block until all submitted work has finished
        (multiprocessing semantics: only legal after close/terminate).
        """
        if not self._closed:
            raise ValueError("Pool is still running")
        if self._inflight:
            api.wait(self._inflight, num_returns=len(self._inflight))
            self._inflight = []

    def __enter__(self) -> "Pool":
        self._check_running()
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()


def _fire_callbacks(res: AsyncResult, callback, error_callback) -> None:
    """Deliver multiprocessing-style callbacks from a background
    thread once the result resolves (the reference fires these from
    its dedicated result thread)."""
    if callback is None and error_callback is None:
        return

    def waiter():
        try:
            value = res.get()
        except Exception as e:  # noqa: BLE001 — goes to error_callback
            if error_callback is not None:
                error_callback(e)
            return
        if callback is not None:
            callback(value)

    import threading
    threading.Thread(target=waiter, daemon=True).start()
