"""Flight recorder: per-process lock-free ring-buffer event journal.

Capability parity with the reference's timeline/profiling layer
(PAPER.md survey L3: the dashboard answers "where did my step time
go"), extended with the crash-journal idiom from aviation: every
process keeps the last N events in a preallocated ring so a death or
stall can be reconstructed after the fact.

Three layers:

1. **Recorder** (every process) — a fixed-capacity list of slots
   claimed by an ``itertools.count`` ticket (``next()`` on a count is
   a single C call, atomic under the GIL) and written with one tuple
   store (a list-index assignment, also atomic). No locks anywhere on
   the record path, so it is safe from the ``rtpu-io-loop`` thread
   (graftlint GL013 enforces that loop-reachable code emits through
   THIS api, never the RPC-capable ``tracing.span``). When the
   recorder is disabled the hot-path cost is two loads and a compare::

       rec = flight_recorder.RECORDER
       if rec is not None:
           rec.record("io", "dispatch", t0_ns, dur_ns)

2. **Collector** (driver) — workers run a daemon flusher thread that
   periodically pushes journal increments over the worker→driver
   control channel (``flight_push``), preceded by a ping-pong clock
   sync (``flight_sync``): the worker samples its clock before and
   after reading the driver's, and ``offset = t_driver - midpoint``
   aligns its ``perf_counter_ns`` domain (arbitrary per-process epoch)
   onto the driver's. The driver keeps the last-N events per process —
   which doubles as the post-mortem source when a process dies without
   a chance to say goodbye.

3. **Export** — ``chrome_events()`` merges every journal (driver's own
   plus collected worker journals), applies the per-process offsets,
   and renders Chrome-trace/Perfetto ``X``/``i`` events on per-process
   tracks; ``ray_tpu.timeline()`` and the dashboard's ``/api/timeline``
   include them automatically. ``merged_journals()`` feeds the
   ``devtools.whereis`` step-time attribution report.

Event slot layout (plain tuple; one allocation per record)::

    (seq, t0_ns, dur_ns, category, name, args_or_None)

Categories used by the built-in instrumentation: ``io`` (IO-loop
dispatch / stream chunks), ``object`` (put/get/transfer), ``pipeline``
(stage instructions, tagged phase=warmup/steady/drain), ``shuffle``
(map/reduce waves), ``prefetch`` (producer/consumer waits),
``collective`` (allreduce &co with compression ratio), ``serve``
(engine prefill/decode steps), ``rl`` (podracer spans: rollout /
infer_batch / replay_wait / learn_step / weight_push).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 4096
# events kept per remote process in the driver-side collector
STORE_CAPACITY = 16384
# journal lines embedded in post-mortem error reports
TAIL_EVENTS = 40

_skew_ns: Optional[int] = None


def _test_skew_ns() -> int:
    """Test-only injected clock skew (``RTPU_FLIGHT_TEST_SKEW_NS``):
    a raw ns value, or ``random:<amp>`` for a per-process deterministic
    skew in ±amp (seeded by pid, so forked workers diverge). Applied
    inside ``clock_ns`` itself so the ping-pong sync must OBSERVE and
    CORRECT it — the clock-alignment test is meaningless otherwise."""
    global _skew_ns
    if _skew_ns is None:
        raw = os.environ.get("RTPU_FLIGHT_TEST_SKEW_NS", "")
        if raw.startswith("random:"):
            import random
            amp = int(float(raw.split(":", 1)[1]))
            _skew_ns = random.Random(os.getpid()).randint(-amp, amp)
        elif raw:
            _skew_ns = int(float(raw))
        else:
            _skew_ns = 0
    return _skew_ns


def clock_ns() -> int:
    """This process's journal clock: monotonic, arbitrary epoch."""
    return time.perf_counter_ns() + _test_skew_ns()


class Recorder:
    """Lock-free bounded journal. Writers from any thread; a snapshot
    may observe a torn ring mid-wrap (a slot overwritten between claim
    and scan) — acceptable: the journal is best-effort observability,
    never a consistency anchor."""

    __slots__ = ("capacity", "label", "_slots", "_seq")

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 label: str = ""):
        self.capacity = max(16, int(capacity))
        self.label = label or f"pid:{os.getpid()}"
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._seq = itertools.count()

    # record() is THE hot path: claim a ticket (atomic), store a tuple
    # (atomic). No locks, no RPC — safe on the rtpu-io-loop thread.
    def record(self, cat: str, name: str, t0_ns: int, dur_ns: int,
               args: Optional[dict] = None) -> None:
        seq = next(self._seq)
        self._slots[seq % self.capacity] = (
            seq, t0_ns, dur_ns, cat, name, args)

    def instant(self, cat: str, name: str,
                args: Optional[dict] = None) -> None:
        self.record(cat, name, clock_ns(), 0, args)

    def clock(self) -> int:
        return clock_ns()

    def snapshot(self, since_seq: int = -1) -> List[tuple]:
        """Events with seq > since_seq, oldest first. Copies the slot
        list first so concurrent writers can't resize reality
        mid-scan."""
        slots = list(self._slots)
        events = [s for s in slots if s is not None and s[0] > since_seq]
        events.sort()
        return events

    def tail(self, n: int = TAIL_EVENTS) -> List[tuple]:
        return self.snapshot()[-n:]


# The module-level gate. Hot paths read this once and None-check it;
# rebinding is atomic under the GIL so enable/disable race nothing.
RECORDER: Optional[Recorder] = None


def enabled() -> bool:
    return RECORDER is not None


def enable(label: str = "", capacity: Optional[int] = None) -> Recorder:
    global RECORDER
    if capacity is None:
        from ray_tpu.core.config import get_config
        capacity = get_config().flight_recorder_capacity
    RECORDER = Recorder(capacity=capacity, label=label)
    _get_anchor()  # pin the wall/perf anchor while both clocks are live
    return RECORDER


def disable() -> None:
    global RECORDER
    RECORDER = None


def record(cat: str, name: str, t0_ns: int, dur_ns: int,
           args: Optional[dict] = None) -> None:
    """Convenience gate for cold paths; hot loops should inline the
    ``RECORDER`` None-check instead of paying a function call."""
    rec = RECORDER
    if rec is not None:
        rec.record(cat, name, t0_ns, dur_ns, args)


def instant(cat: str, name: str, args: Optional[dict] = None) -> None:
    rec = RECORDER
    if rec is not None:
        rec.record(cat, name, clock_ns(), 0, args)


def phase_begin(cat: str, name: str) -> Optional[int]:
    """Open an explicit span: returns the start ns (None when the
    recorder is off — phase_end treats None as a no-op). The matching
    ``phase_end`` MUST run on every code path out of the function;
    wrap the body in try/finally, or graftlint GL020 flags the early
    return/raise that would silently drop the span."""
    rec = RECORDER
    return rec.clock() if rec is not None else None


def phase_end(cat: str, name: str, t0: Optional[int],
              args: Optional[dict] = None) -> None:
    """Close a span opened by ``phase_begin``."""
    rec = RECORDER
    if rec is not None and t0 is not None:
        rec.record(cat, name, t0, clock_ns() - t0, args)


# --- wall-clock anchoring -----------------------------------------------
# perf_counter_ns has an arbitrary per-process epoch. The driver pins
# one (wall, perf) pair; every aligned journal timestamp is rendered as
# wall_anchor + (t_ns - perf_anchor), putting flight events on the same
# wall-clock microsecond scale the task-event timeline already uses.

_anchor: Optional[Tuple[float, int]] = None


def _get_anchor() -> Tuple[float, int]:
    global _anchor
    if _anchor is None:
        _anchor = (time.time(), clock_ns())
    return _anchor


# --- driver-side collector ----------------------------------------------

class FlightStore:
    """Driver-held journals pushed by worker flushers. Bounded per
    process; survives the process that pushed it — the post-mortem
    source for actor deaths."""

    def __init__(self):
        self.lock = threading.Lock()
        self._procs: Dict[str, dict] = {}

    def push(self, label: str, events: List[tuple],
             offset_ns: int) -> None:
        # Brief and lock-only: this runs in the GCS dispatch path,
        # which may be the head's IO-loop thread.
        with self.lock:
            entry = self._procs.get(label)
            if entry is None:
                entry = {"events": deque(maxlen=STORE_CAPACITY),
                         "offset": 0, "last_seq": -1}
                self._procs[label] = entry
            entry["offset"] = int(offset_ns)
            for ev in events:
                if ev[0] > entry["last_seq"]:
                    entry["events"].append(tuple(ev))
                    entry["last_seq"] = ev[0]

    def journals(self) -> List[Tuple[str, int, List[tuple]]]:
        """(label, offset_ns, events) per pushed process."""
        with self.lock:
            return [(label, entry["offset"], list(entry["events"]))
                    for label, entry in sorted(self._procs.items())]

    def tail(self, label_substr: str,
             n: int = TAIL_EVENTS) -> Optional[List[str]]:
        """Formatted last-n events of the journal whose label contains
        ``label_substr`` — the supervisor's post-mortem lookup."""
        with self.lock:
            for label, entry in self._procs.items():
                if label_substr in label:
                    events = list(entry["events"])[-n:]
                    break
            else:
                return None
        return format_events(events)


_STORE: Optional[FlightStore] = None


def get_store() -> FlightStore:
    global _STORE
    if _STORE is None:
        _STORE = FlightStore()
    return _STORE


def store_push(label: str, events: List[tuple], offset_ns: int) -> None:
    get_store().push(label, events, offset_ns)


# --- process wiring ------------------------------------------------------

def init_driver() -> None:
    """Reset collector state and (when configured) enable the driver's
    own recorder. Called from Runtime.__init__; env flags are mirrored
    so workers forked later inherit the same configuration."""
    global _STORE, _anchor
    from ray_tpu.core.config import get_config
    cfg = get_config()
    _STORE = FlightStore()
    _anchor = None
    stop_flusher()
    if cfg.flight_recorder_enabled:
        os.environ["RTPU_FLIGHT_RECORDER_ENABLED"] = "1"
        os.environ["RTPU_FLIGHT_RECORDER_CAPACITY"] = str(
            cfg.flight_recorder_capacity)
        os.environ["RTPU_FLIGHT_FLUSH_INTERVAL_S"] = str(
            cfg.flight_flush_interval_s)
        enable(label=f"driver:{os.getpid()}",
               capacity=cfg.flight_recorder_capacity)
    else:
        os.environ.pop("RTPU_FLIGHT_RECORDER_ENABLED", None)
        disable()


def init_worker(rt, worker_id) -> None:
    """Enable the recorder and start the flusher thread in a worker
    process (no-op unless the driver enabled recording — the flag rides
    the inherited environment)."""
    from ray_tpu.core.config import get_config
    cfg = get_config()
    if not cfg.flight_recorder_enabled:
        return
    label = f"worker:{worker_id.hex()[:12]}:pid:{os.getpid()}"
    rec = enable(label=label, capacity=cfg.flight_recorder_capacity)
    start_flusher(rt, rec, interval_s=cfg.flight_flush_interval_s)


class _Flusher(threading.Thread):
    """Worker-side daemon: every interval, ping-pong the driver clock
    then push the journal increment. Runs gcs_call from a non-main
    thread — safe: replies are delivered by the worker's main recv
    loop (the same channel metrics forwarding uses)."""

    def __init__(self, rt, recorder: Recorder, interval_s: float):
        super().__init__(name="flight-flush", daemon=True)
        self._rt = rt
        self._recorder = recorder
        self._interval = max(0.02, float(interval_s))
        self._last_seq = -1
        self._stop = threading.Event()

    def flush_once(self) -> None:
        t0 = clock_ns()
        t_driver = self._rt.gcs_call("flight_sync")
        t1 = clock_ns()
        # driver_clock ≈ worker_clock + offset, assuming the symmetric-
        # delay midpoint is when the driver sampled its clock.
        offset = int(t_driver) - (t0 + t1) // 2
        events = self._recorder.snapshot(since_seq=self._last_seq)
        if events:
            self._last_seq = events[-1][0]
        self._rt.gcs_call("flight_push", self._recorder.label, events,
                          offset)

    def run(self) -> None:
        from ray_tpu.util.backoff import Backoff

        # Failed pushes back off with jitter (util/backoff.py) instead
        # of re-hammering a struggling control channel every interval.
        backoff = Backoff(initial_s=self._interval,
                          max_s=8 * self._interval)
        failures = 0
        delay = self._interval
        while not self._stop.wait(delay):
            try:
                self.flush_once()
                failures = 0
                backoff.reset()
                delay = self._interval
            except Exception:  # noqa: BLE001 — slow env setup, or the
                failures += 1  # channel is gone at shutdown
                if failures >= 3:
                    return
                delay = backoff.next_delay()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.flush_once()  # final increment, best effort
        except Exception:  # graftlint: disable=GL004
            pass  # shutdown race: the control channel may be gone


_flusher: Optional[_Flusher] = None


def start_flusher(rt, recorder: Recorder, interval_s: float) -> None:
    global _flusher
    _flusher = _Flusher(rt, recorder, interval_s)
    _flusher.start()


def stop_flusher() -> None:
    global _flusher
    if _flusher is not None:
        _flusher.stop()
        _flusher = None


def flush_now() -> None:
    """Push the local journal increment immediately (worker-side; used
    right before surfacing an error so the driver's copy is current)."""
    if _flusher is not None:
        try:
            _flusher.flush_once()
        except Exception:  # graftlint: disable=GL004
            pass  # observability must never mask the original error


# --- merge + export ------------------------------------------------------

def merged_journals() -> Dict[str, List[tuple]]:
    """label -> clock-aligned events (driver perf_counter_ns domain),
    including the driver's own journal at offset 0."""
    out: Dict[str, List[tuple]] = {}
    store = _STORE
    if store is not None:
        for label, offset, events in store.journals():
            out[label] = [(seq, t0 + offset, dur, cat, name, args)
                          for seq, t0, dur, cat, name, args in events]
    rec = RECORDER
    if rec is not None:
        out[rec.label] = rec.snapshot()
    return out


def _role_for_label(label: str) -> str:
    """Human track name for a journal label: ``driver:4242`` → driver,
    ``worker:ab12cd34ef56:pid:77`` → worker-ab12cd34."""
    if label.startswith("driver"):
        return "driver"
    if label.startswith("worker:"):
        return "worker-" + label.split(":")[1][:8]
    return label.split(":")[0] or label


def chrome_events() -> List[Dict[str, Any]]:
    """Merged journals as Chrome-trace/Perfetto events: one ``pid``
    track per process, one ``tid`` row per category, complete ``X``
    slices for spans and ``i`` instants for point events. Each track
    leads with ``process_name``/``thread_name`` metadata (``ph: M``) so
    Perfetto labels rows by role (driver / worker-N / io-loop) instead
    of bare journal labels."""
    wall_anchor, perf_anchor = _get_anchor()
    out: List[Dict[str, Any]] = []
    for label, events in merged_journals().items():
        pid = f"flight:{label}"
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0,
                    "args": {"name": _role_for_label(label),
                             "label": label}})
        for cat in sorted({ev[3] for ev in events}):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": cat, "args": {"name": cat}})
        for seq, t0, dur, cat, name, args in events:
            ts_us = (wall_anchor + (t0 - perf_anchor) / 1e9) * 1e6
            ev: Dict[str, Any] = {
                "name": name, "cat": f"flight:{cat}", "ts": ts_us,
                "pid": pid, "tid": cat,
                "args": dict(args) if args else {"seq": seq},
            }
            if dur > 0:
                ev["ph"] = "X"
                ev["dur"] = dur / 1e3
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            out.append(ev)
    return out


def dump_journals(filename: Optional[str] = None) -> Dict[str, Any]:
    """Write the merged (clock-aligned) journals as JSON for offline
    analysis — the input format of ``python -m ray_tpu.devtools.whereis``."""
    import json
    payload = {
        "anchor": list(_get_anchor()),
        "journals": {label: [list(ev) for ev in events]
                     for label, events in merged_journals().items()},
    }
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
    return payload


# --- post-mortem ---------------------------------------------------------

def format_events(events: List[tuple]) -> List[str]:
    """Human lines for an error report, newest last, timestamps
    relative to the newest event."""
    if not events:
        return []
    t_end = max(ev[1] + ev[2] for ev in events)
    lines = []
    for seq, t0, dur, cat, name, args in events:
        rel_ms = (t0 - t_end) / 1e6
        line = f"[{rel_ms:+10.3f}ms] {cat}:{name}"
        if dur > 0:
            line += f" dur={dur / 1e6:.3f}ms"
        if args:
            line += f" {args}"
        lines.append(line)
    return lines


def local_tail(n: int = TAIL_EVENTS) -> Optional[List[str]]:
    """Formatted tail of THIS process's journal, or None when the
    recorder is off. Attached to exceptions at raise time (the tuple
    rides the pickled exception's __dict__ back to the driver)."""
    rec = RECORDER
    if rec is None:
        return None
    return format_events(rec.tail(n))


def attach_tail(exc: BaseException, n: int = TAIL_EVENTS) -> None:
    """Stamp the local journal tail onto ``exc`` (picklable: plain
    strings in __dict__) and push the increment to the driver so the
    supervisor's copy includes the final moments."""
    tail = local_tail(n)
    if tail is not None:
        exc._flight_tail = tail  # type: ignore[attr-defined]
    flush_now()


def tail_text(exc_or_lines, limit: int = TAIL_EVENTS) -> str:
    """Render a journal tail (from an exception's ``_flight_tail`` or a
    raw line list) as an indented block for error messages. Empty
    string when there is nothing to show."""
    lines = (getattr(exc_or_lines, "_flight_tail", None)
             if isinstance(exc_or_lines, BaseException) else exc_or_lines)
    if not lines:
        return ""
    lines = lines[-limit:]
    return ("\n  flight recorder (last %d events):\n    " % len(lines)
            + "\n    ".join(lines))


def store_tail_text(label_substr: str, n: int = TAIL_EVENTS) -> str:
    """Post-mortem text from the driver-side collector for a process
    that died (matched by label substring, e.g. a worker id prefix)."""
    store = _STORE
    if store is None:
        return ""
    lines = store.tail(label_substr, n)
    return tail_text(lines) if lines else ""
