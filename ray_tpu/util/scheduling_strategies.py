"""User-facing scheduling strategy classes.

reference: python/ray/util/scheduling_strategies.py — the public names
(`NodeAffinitySchedulingStrategy`, `NodeLabelSchedulingStrategy`,
`PlacementGroupSchedulingStrategy`) users pass as
``scheduling_strategy=...`` in ``.options()``. Here each is a thin
constructor over the core `SchedulingStrategy` record that the
scheduler already understands (`core/scheduler.py` NODE_AFFINITY /
NODE_LABEL / PLACEMENT_GROUP branches).
"""
from typing import Dict, Union

from ray_tpu.core.ids import NodeID
from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.util.placement_group import PlacementGroupSchedulingStrategy

__all__ = [
    "SchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeAntiAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]


class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    """Pin a task/actor to one node (reference:
    scheduling_strategies.py NodeAffinitySchedulingStrategy).

    ``soft=True`` falls back to the default policy if the node is gone;
    hard affinity fails the task if the node cannot host it.
    """

    def __init__(self, node_id: Union[str, NodeID], soft: bool = False):
        if isinstance(node_id, str):
            node_id = NodeID.from_hex(node_id)
        super().__init__(kind="NODE_AFFINITY", node_id=node_id, soft=soft)


class NodeAntiAffinitySchedulingStrategy(SchedulingStrategy):
    """Keep a task/actor OFF one node (stated divergence: the reference
    expresses anti-affinity through label ``!in`` operators; here it is
    a first-class strategy because drills routinely need "anywhere but
    the node under chaos").

    ``soft=True`` prefers other nodes but allows the avoided node when
    it is the only feasible host; hard anti-affinity parks the task as
    infeasible until another capable node exists.
    """

    def __init__(self, node_id: Union[str, NodeID], soft: bool = False):
        if isinstance(node_id, str):
            node_id = NodeID.from_hex(node_id)
        super().__init__(kind="NODE_ANTI_AFFINITY", node_id=node_id,
                         soft=soft)


class NodeLabelSchedulingStrategy(SchedulingStrategy):
    """Require exact-match node labels (reference:
    scheduling_strategies.py NodeLabelSchedulingStrategy hard
    requirements; soft/in-operator forms are not supported — stated
    divergence: the scheduler's label branch is exact-match only).
    """

    def __init__(self, hard: Dict[str, str]):
        if not isinstance(hard, dict) or not hard:
            raise ValueError(
                "NodeLabelSchedulingStrategy requires a non-empty dict "
                "of {label: value} hard requirements")
        super().__init__(kind="NODE_LABEL", labels=dict(hard))
