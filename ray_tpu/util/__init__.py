"""ray_tpu.util — orchestration + observability utilities
(placement groups, scheduling strategies, actor pool, queue,
multiprocessing shim, state API, user metrics)."""

from ray_tpu.util import metrics, state
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group)
from ray_tpu.util.queue import Queue
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy)

__all__ = ["ActorPool", "NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy",
           "PlacementGroupSchedulingStrategy", "Queue", "metrics",
           "placement_group", "remove_placement_group", "state"]
