"""ray_tpu.util — orchestration + observability utilities
(placement groups, state API, user metrics)."""

from ray_tpu.util import metrics, state
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group)

__all__ = ["metrics", "placement_group", "remove_placement_group",
           "state"]
