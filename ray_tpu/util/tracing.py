"""User-facing tracing: profile spans inside tasks/actors.

Capability parity with the reference's profiling hooks
(reference: src/ray/core_worker/profile_event.cc ProfileEvent — user
spans buffered in the TaskEventBuffer and surfaced in `ray timeline`;
python/ray/util/tracing/tracing_helper.py span propagation).

Usage inside any task or actor method::

    from ray_tpu.util.tracing import profile
    with profile("load_batch"):
        ...

Spans ship with the task's completion reply (zero extra RPCs), land in
the GCS task-event store, and appear as nested slices on the worker's
track in ``ray_tpu.timeline()``.
"""

from __future__ import annotations

import time
from contextlib import contextmanager


@contextmanager
def profile(name: str):
    """Record a named span for the duration of the with-block. No-op
    outside a worker task (e.g. on the driver)."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    spans = getattr(rt, "_profile_spans", None) if rt is not None else None
    items = spans.value if spans is not None else None
    t0 = time.time()
    try:
        yield
    finally:
        if items is not None:
            items.append((str(name), t0, time.time()))
