"""Tracing: profile spans, W3C-style trace context, cluster-wide spans.

Capability parity with the reference's profiling + tracing hooks
(reference: src/ray/core_worker/profile_event.cc ProfileEvent — user
spans buffered in the TaskEventBuffer and surfaced in `ray timeline`;
python/ray/util/tracing/tracing_helper.py span propagation across
``.remote()`` boundaries).

Two layers:

1. ``profile(name)`` — a named span inside a task/actor. Spans ship
   with the task's completion reply (zero extra RPCs), land in the GCS
   task-event store, and nest on the worker's track in
   ``ray_tpu.timeline()``. Durations are anchored on
   ``time.perf_counter()`` (immune to NTP wall-clock steps); the start
   timestamp stays wall-clock so timeline alignment across processes
   holds.

2. Distributed trace context — a W3C-traceparent-compatible
   (``trace_id``, ``span_id``) pair carried in a contextvar. Every
   ``.remote()`` stamps the active context into the TaskSpec (minting a
   fresh root when none is active), workers re-establish it before user
   code runs, and the Serve proxy parses/echoes ``traceparent`` headers
   — so one ``trace_id`` follows a request across proxy → router →
   replica → engine hops and any nested tasks. ``span()`` records
   named spans into the GCS trace store, queryable via
   ``/api/traces/<trace_id>`` on the dashboard.

Usage inside any task or actor method::

    from ray_tpu.util.tracing import profile, span
    with profile("load_batch"):
        ...
    with span("rank_candidates", component="app"):
        ...
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class TraceContext:
    """One hop of a distributed trace (W3C trace-context flavored):
    ``trace_id`` identifies the whole request tree, ``span_id`` the
    current operation within it."""
    trace_id: str
    span_id: str

    def traceparent(self) -> str:
        return format_traceparent(self)


_trace_var: ContextVar[Optional[TraceContext]] = ContextVar(
    "ray_tpu_trace_context", default=None)


def new_trace_id() -> str:
    """32 lowercase hex chars (W3C traceparent trace-id width)."""
    import uuid
    return uuid.uuid4().hex


def new_span_id() -> str:
    """16 lowercase hex chars (W3C traceparent parent-id width)."""
    import uuid
    return uuid.uuid4().hex[:16]


def task_span_id(task_id) -> str:
    """A task's execution IS a span; derive its span id from the task
    id so task events and recorded spans correlate without an extra
    field on the wire."""
    return task_id.hex()[:16]


def get_trace_context() -> Optional[TraceContext]:
    return _trace_var.get()


def set_trace_context(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the current trace context; returns the token
    for ``reset_trace_context``."""
    return _trace_var.set(ctx)


def reset_trace_context(token) -> None:
    _trace_var.reset(token)


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a W3C ``traceparent`` header (``00-<trace>-<span>-<flags>``).
    Returns None on absent/malformed input — a bad client header must
    degrade to a fresh root trace, never a 500."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are invalid per the spec
    return TraceContext(trace_id.lower(), span_id.lower())


def format_traceparent(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


_UNSET = object()


def record_span(name: str, component: str, t_start: float,
                duration: float, ctx: TraceContext,
                parent_span_id: Optional[str] = None,
                tags: Optional[Dict[str, Any]] = None) -> None:
    """Ship one finished span to the GCS trace store (driver: direct
    append; worker: one control-plane RPC). Best-effort — tracing must
    never fail the traced operation."""
    span_tuple = (ctx.trace_id, ctx.span_id, parent_span_id, str(name),
                  str(component), t_start, duration,
                  dict(tags) if tags else None)
    try:
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime_or_none()
        if rt is None:
            return
        if getattr(rt, "is_driver", False):
            rt.gcs.add_trace_span(span_tuple)
        else:
            rt.gcs_call("trace_add_span", span_tuple)
    except Exception:  # graftlint: disable=GL004
        pass  # span export is best-effort observability


@contextmanager
def span(name: str, component: str = "app",
         tags: Optional[Dict[str, Any]] = None, parent=_UNSET):
    """Record a named span under the active trace (minting a fresh root
    trace when none is active). The span becomes the current context for
    the with-block, so nested spans and ``.remote()`` calls made inside
    attach as children. Yields the span's TraceContext.

    ``parent``: explicit parent TraceContext (or None to force a new
    root) — used by ingress points like the Serve proxy that carry the
    parent in a ``traceparent`` header rather than a contextvar.
    """
    parent_ctx = _trace_var.get() if parent is _UNSET else parent
    ctx = TraceContext(
        parent_ctx.trace_id if parent_ctx is not None else new_trace_id(),
        new_span_id())
    token = _trace_var.set(ctx)
    wall0 = time.time()
    p0 = time.perf_counter()
    try:
        yield ctx
    finally:
        duration = time.perf_counter() - p0
        _trace_var.reset(token)
        record_span(name, component, wall0, duration, ctx,
                    parent_span_id=(parent_ctx.span_id
                                    if parent_ctx is not None else None),
                    tags=tags)


@contextmanager
def profile(name: str):
    """Record a named span for the duration of the with-block. No-op
    outside a worker task (e.g. on the driver). Duration is measured on
    the monotonic perf_counter clock — an NTP step mid-span shifts the
    wall-clock anchor, never the duration."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    spans = getattr(rt, "_profile_spans", None) if rt is not None else None
    items = spans.value if spans is not None else None
    wall0 = time.time()
    p0 = time.perf_counter()
    try:
        yield
    finally:
        if items is not None:
            items.append((str(name), wall0,
                          wall0 + (time.perf_counter() - p0)))
