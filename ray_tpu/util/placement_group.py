"""Placement groups — gang scheduling of resource bundles.

Capability parity with the reference's placement groups
(reference: python/ray/util/placement_group.py:146; strategies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD, src/ray/protobuf/common.proto:1051;
atomic all-or-nothing reservation via GCS 2PC,
gcs_placement_group_scheduler.h:281). Used for TPU slice gang
reservation: one bundle per TPU host of a slice, STRICT_SPREAD, with the
slice-head custom resource pinning the gang to one slice (the reference's
reserve_tpu_slice pattern, _private/accelerators/tpu.py:145).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.gcs import Bundle, PlacementGroupRecord
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.exceptions import PlacementGroupUnschedulableError


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundle_specs: List[Dict[str, float]]
    strategy: str

    def ready(self, timeout: Optional[float] = None) -> bool:
        rt = runtime_mod.get_runtime()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            record = rt.gcs.get_placement_group(self.id)
            if record is not None and record.state == "CREATED":
                return True
            if record is not None and record.state == "REMOVED":
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def bundle_node_ids(self):
        rt = runtime_mod.get_runtime()
        record = rt.gcs.get_placement_group(self.id)
        return [b.node_id for b in record.bundles] if record else []


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    bundle_label_selector: Optional[List[Dict[str, str]]]
                    = None) -> PlacementGroup:
    """Create a placement group; reservation is immediate when capacity
    exists, otherwise the PG queues as PENDING and is retried whenever
    capacity changes (node joins, another PG removed) — the autoscaler
    reads queued PGs as gang demand and provisions slices to satisfy
    them (reference: gcs_placement_group_scheduler.h:281 pending queue;
    python/ray/util/placement_group.py:146 async creation + ready()).
    """
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"unknown placement strategy: {strategy}")
    rt = runtime_mod.get_runtime()
    pg_id = PlacementGroupID.from_random()
    selectors = bundle_label_selector or [{}] * len(bundles)
    if len(selectors) != len(bundles):
        raise ValueError(
            f"bundle_label_selector length ({len(selectors)}) must match "
            f"bundles length ({len(bundles)})")
    record = PlacementGroupRecord(
        pg_id=pg_id, name=name, strategy=strategy,
        bundles=[Bundle(index=i, resources=dict(b),
                        label_selector=dict(sel))
                 for i, (b, sel) in enumerate(zip(bundles, selectors))])
    rt.gcs.register_placement_group(record)
    try:
        rt.scheduler.reserve_placement_group(record)
    except PlacementGroupUnschedulableError:
        rt.queue_pending_placement_group(record)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = runtime_mod.get_runtime()
    record = rt.gcs.get_placement_group(pg.id)
    if record is not None:
        # State transition runs under the runtime's PG lock so it can't
        # race a concurrent pending-PG retry into a leaked reservation.
        rt.remove_placement_group_record(record)


class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    """Adapter so tasks/actors target a PG bundle
    (reference: python/ray/util/scheduling_strategies.py:17)."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        super().__init__(
            kind="PLACEMENT_GROUP",
            placement_group_id=placement_group.id,
            bundle_index=placement_group_bundle_index,
            capture_child_tasks=placement_group_capture_child_tasks)
