"""Serialization debugging: find WHICH nested object can't pickle.

reference: python/ray/util/check_serialize.py
`inspect_serializability` — recursively tries cloudpickle on an
object's closure/attributes and reports the offending leaves, instead
of the opaque mid-pickle TypeError users otherwise get.
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Optional, Set, Tuple

from ray_tpu.core import serialization

__all__ = ["inspect_serializability", "FailTuple"]


@dataclass(frozen=True)
class FailTuple:
    """One unserializable leaf: its name, string form, and the parent
    object it was reached through."""
    name: str
    obj: str = field(compare=False)
    parent: str = field(compare=False)

    def __repr__(self):
        return (f"FailTuple({self.name} [obj={self.obj!r}, "
                f"parent={self.parent!r}])")


def _serializable(obj: Any) -> bool:
    try:
        serialization.dumps(obj)
        return True
    except Exception:
        return False


def _inspect(obj: Any, name: str, depth: int,
             parent: str, failures: Set[FailTuple],
             seen: Set[int]) -> None:
    if id(obj) in seen or depth < 0:
        return
    seen.add(id(obj))
    if _serializable(obj):
        return
    if depth == 0:
        failures.add(FailTuple(name, repr(obj)[:80], parent))
        return

    found_deeper = False
    # closures: the usual culprits (locks, sockets, clients captured
    # by a remote function)
    if inspect.isfunction(obj) or inspect.ismethod(obj):
        fn = obj.__func__ if inspect.ismethod(obj) else obj
        closure = fn.__closure__ or ()
        names = fn.__code__.co_freevars
        for cname, cell in zip(names, closure):
            try:
                cv = cell.cell_contents
            except ValueError:
                continue
            if not _serializable(cv):
                found_deeper = True
                _inspect(cv, cname, depth - 1, name, failures, seen)
        for gname, gv in (fn.__globals__ or {}).items():
            if gname in fn.__code__.co_names and not _serializable(gv):
                found_deeper = True
                _inspect(gv, gname, depth - 1, name, failures, seen)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            if not _serializable(v):
                found_deeper = True
                _inspect(v, str(k), depth - 1, name, failures, seen)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for i, v in enumerate(obj):
            if not _serializable(v):
                found_deeper = True
                _inspect(v, f"{name}[{i}]", depth - 1, name, failures,
                         seen)
    else:
        attrs = getattr(obj, "__dict__", None)
        if attrs:
            for aname, av in attrs.items():
                if not _serializable(av):
                    found_deeper = True
                    _inspect(av, aname, depth - 1, name, failures, seen)

    if not found_deeper:
        # this object itself is the leaf failure
        failures.add(FailTuple(name, repr(obj)[:80], parent))


def inspect_serializability(
        obj: Any, name: Optional[str] = None,
        depth: int = 3, print_info: bool = True
) -> Tuple[bool, Set[FailTuple]]:
    """Check whether ``obj`` cloudpickles; on failure, descend into
    closures/attributes/containers up to ``depth`` levels and return
    the offending leaves.

    Returns (serializable, failures).
    """
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    failures: Set[FailTuple] = set()
    _inspect(obj, name, depth, "<root>", failures, set())
    ok = not failures
    if print_info and not ok:
        print(f"{name!r} is not serializable. Offending objects:")
        for f in sorted(failures, key=lambda f: f.name):
            print(f"  - {f!r}")
    return ok, failures
