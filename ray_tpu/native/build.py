"""Build the native library on demand.

The .so is compiled once per machine into ray_tpu/native/_build/ and
reused; rebuilt automatically when any source file is newer than the
binary. Keeps the repo pip-install-free (no pybind11; plain ctypes ABI).

Build failures (g++ missing, compile error) raise NativeBuildError and
are cached: the first failure logs one warning, later calls fail fast
instead of re-running the compiler on every import/call so callers can
route onto their pure-Python fallbacks cheaply.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading

from ray_tpu.devtools import locktrace

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_DIR, "src")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libray_tpu_native.so")
_lock = locktrace.traced_lock("native.build")
# target key -> failure detail; guarded by _lock. A key present here
# means "don't retry the compile this process".
_build_failed: dict = {}


class NativeBuildError(RuntimeError):
    """Raised when the native toolchain is unavailable or the compile
    fails; callers catch this and fall back to pure Python."""


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        # *_main.cc are standalone test binaries (stress harness), not
        # part of the runtime library
        if f.endswith(".cc") and not f.endswith("_main.cc")
    )


def _fresh(out: str, srcs) -> bool:
    if not os.path.exists(out):
        return False
    out_mtime = os.path.getmtime(out)
    return all(os.path.getmtime(s) <= out_mtime for s in srcs)


def _compile(key: str, cmd, out: str) -> str:
    """Run one g++ invocation OUTSIDE any lock (compiles take seconds;
    holding a lock across them would serialize unrelated callers and
    trip the blocking-under-lock lint). Concurrent duplicate compiles
    are benign: each writes a unique tmp and os.replace is atomic."""
    tmp = f"{out}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        proc = subprocess.run(cmd + ["-o", tmp], capture_output=True,
                              text=True)
    except OSError as exc:  # g++ not installed at all
        _record_failure(key, f"toolchain unavailable: {exc}")
        raise NativeBuildError(f"native build failed ({key}): {exc}") \
            from exc
    if proc.returncode != 0:
        detail = (proc.stderr or proc.stdout or "").strip()[-2000:]
        _record_failure(key, detail)
        raise NativeBuildError(
            f"native build failed ({key}, rc={proc.returncode}):\n{detail}")
    os.replace(tmp, out)
    return out


def _record_failure(key: str, detail: str) -> None:
    with _lock:
        first = not _build_failed
        _build_failed[key] = detail
    if first:
        logger.warning(
            "native build failed (%s); using pure-Python fallbacks for "
            "this process: %s", key, detail.splitlines()[-1] if detail
            else detail)


def _check_cached_failure(key: str) -> None:
    with _lock:
        detail = _build_failed.get(key)
    if detail is not None:
        raise NativeBuildError(
            f"native build previously failed ({key}): {detail}")


def ensure_built() -> str:
    _check_cached_failure("lib")
    with _lock:
        srcs = _sources()
        if _fresh(_LIB_PATH, srcs):
            return _LIB_PATH
        os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-g", "-fPIC", "-shared", "-std=c++17",
           "-Wall", "-pthread", *srcs]
    return _compile("lib", cmd, _LIB_PATH)


def build_stress(sanitizer: str = "",
                 main_src: str = "stress_test_main.cc") -> str:
    """Build a stress binary from ``src/<main_src>`` linked against the
    library sources, optionally under ASan/TSan — the seam the
    reference covers with its sanitizer bazel configs (SURVEY.md §5.2,
    .bazelrc:112-132). The default main is the shm-store harness; pass
    ``wire_stress_main.cc`` for the wire-codec harness. Returns the
    binary path; raises NativeBuildError with compiler output on
    failure."""
    if sanitizer not in ("", "address", "thread"):
        raise ValueError(f"unknown sanitizer {sanitizer!r}")
    stem = "shm_stress" if main_src == "stress_test_main.cc" \
        else main_src[:-len("_main.cc")]
    suffix = f"-{sanitizer}" if sanitizer else ""
    out = os.path.join(_BUILD_DIR, f"{stem}{suffix}")
    key = f"{stem}{suffix}"
    _check_cached_failure(key)
    with _lock:
        srcs = _sources() + [os.path.join(_SRC_DIR, main_src)]
        if _fresh(out, srcs):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O1", "-g", "-std=c++17", "-Wall", "-pthread"]
    if sanitizer:
        cmd += [f"-fsanitize={sanitizer}", "-fno-omit-frame-pointer"]
    cmd += srcs
    return _compile(key, cmd, out)
