"""Build the native library on demand.

The .so is compiled once per machine into ray_tpu/native/_build/ and
reused; rebuilt automatically when any source file is newer than the
binary. Keeps the repo pip-install-free (no pybind11; plain ctypes ABI).
"""

from __future__ import annotations

import os
import subprocess
import threading

from ray_tpu.devtools import locktrace

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_DIR, "src")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libray_tpu_native.so")
_lock = locktrace.traced_lock("native.build")


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        # *_main.cc are standalone test binaries (stress harness), not
        # part of the runtime library
        if f.endswith(".cc") and not f.endswith("_main.cc")
    )


def ensure_built() -> str:
    with _lock:
        srcs = _sources()
        if os.path.exists(_LIB_PATH):
            lib_mtime = os.path.getmtime(_LIB_PATH)
            if all(os.path.getmtime(s) <= lib_mtime for s in srcs):
                return _LIB_PATH
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = [
            "g++", "-O2", "-g", "-fPIC", "-shared", "-std=c++17",
            "-Wall", "-pthread",
            "-o", _LIB_PATH + ".tmp", *srcs,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
        return _LIB_PATH


def build_stress(sanitizer: str = "") -> str:
    """Build the shm-store stress binary (ray_tpu/native/src/
    stress_test_main.cc), optionally under ASan/TSan — the seam the
    reference covers with its sanitizer bazel configs (SURVEY.md §5.2,
    .bazelrc:112-132). Returns the binary path; raises
    subprocess.CalledProcessError with compiler output on failure."""
    if sanitizer not in ("", "address", "thread"):
        raise ValueError(f"unknown sanitizer {sanitizer!r}")
    suffix = f"-{sanitizer}" if sanitizer else ""
    out = os.path.join(_BUILD_DIR, f"shm_stress{suffix}")
    with _lock:
        srcs = _sources() + [os.path.join(_SRC_DIR, "stress_test_main.cc")]
        if os.path.exists(out):
            bin_mtime = os.path.getmtime(out)
            if all(os.path.getmtime(s) <= bin_mtime for s in srcs):
                return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O1", "-g", "-std=c++17", "-Wall", "-pthread"]
        if sanitizer:
            cmd += [f"-fsanitize={sanitizer}", "-fno-omit-frame-pointer"]
        cmd += ["-o", out + ".tmp", *srcs]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(out + ".tmp", out)
        return out
