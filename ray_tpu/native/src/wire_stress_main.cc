// Stress harness for the wire codec (wire.cc): concurrent producers
// enqueue patterned frames into one Writer, a flusher pumps them over
// a non-blocking socketpair, and a consumer Decoder verifies every
// byte and per-producer sequence ordering on the far side. Built by
// native/build.py (optionally under ASan/TSan) and run by the
// slow-marked test in tests/test_native_stress.py — same protocol as
// stress_test_main.cc: prints STRESS-OK on success, exit 2 on a
// verification mismatch, exit 3 on watchdog timeout.
//
// Usage: wire_stress threads <workers> <iters_per_producer>

#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
void* wire_decoder_new();
void wire_decoder_free(void*);
int64_t wire_decoder_read_fd(void*, int fd);
int64_t wire_decoder_next(void*, const uint8_t** out);
void* wire_writer_new();
void wire_writer_free(void*);
int64_t wire_writer_enqueue(void*, const uint8_t*, uint64_t);
int64_t wire_writer_flush_fd(void*, int fd);
int64_t wire_writer_queued(void*);
}

namespace {

constexpr int kProducers = 2;

uint8_t pattern_byte(uint32_t producer, uint32_t seq, uint32_t j) {
  return (uint8_t)(seq * 131 + j * 29 + producer * 7);
}

void set_nonblocking(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

// One worker: producers -> Writer -> flusher -> socketpair ->
// Decoder -> verifier. Returns 0 on success, 2 on mismatch.
int run_worker(int iters) {
  int fds[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, fds) != 0) return 2;
  set_nonblocking(fds[0]);
  set_nonblocking(fds[1]);

  void* writer = wire_writer_new();
  std::atomic<int> producers_done{0};
  std::atomic<bool> failed{false};

  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; p++) {
    producers.emplace_back([&, p]() {
      std::vector<uint8_t> frame;
      for (int seq = 0; seq < iters; seq++) {
        // Pseudorandom sizes spanning sub-block and multi-block frames.
        uint32_t len = 13 + (uint32_t)((seq * 2654435761u + p * 97) %
                                       8000);
        frame.resize(12 + len);
        memcpy(frame.data(), &p, 4);
        memcpy(frame.data() + 4, &seq, 4);
        memcpy(frame.data() + 8, &len, 4);
        for (uint32_t j = 0; j < len; j++)
          frame[12 + j] = pattern_byte((uint32_t)p, (uint32_t)seq, j);
        if (wire_writer_enqueue(writer, frame.data(), frame.size()) < 0) {
          failed = true;
          return;
        }
        // Backpressure: don't let the queue grow without bound.
        while (wire_writer_queued(writer) > (1 << 22))
          std::this_thread::yield();
      }
      producers_done++;
    });
  }

  std::thread flusher([&]() {
    struct pollfd pfd = {fds[0], POLLOUT, 0};
    for (;;) {
      int64_t rc = wire_writer_flush_fd(writer, fds[0]);
      if (rc < 0) {
        failed = true;
        break;
      }
      if (rc == 0) {
        if (producers_done.load() == kProducers &&
            wire_writer_queued(writer) == 0)
          break;
        std::this_thread::yield();
        continue;
      }
      poll(&pfd, 1, 50);
    }
    shutdown(fds[0], SHUT_WR);
  });

  int rc = 0;
  {
    void* dec = wire_decoder_new();
    std::vector<int> next_seq(kProducers, 0);
    long long frames = 0;
    struct pollfd pfd = {fds[1], POLLIN, 0};
    bool done = false;
    while (!done) {
      int64_t st = wire_decoder_read_fd(dec, fds[1]);
      if (st == -2 || st == -3) {
        rc = 2;
        break;
      }
      const uint8_t* ptr = nullptr;
      int64_t n;
      while ((n = wire_decoder_next(dec, &ptr)) >= 0) {
        if (n < 12) {
          rc = 2;
          done = true;
          break;
        }
        uint32_t producer, seq, len;
        memcpy(&producer, ptr, 4);
        memcpy(&seq, ptr + 4, 4);
        memcpy(&len, ptr + 8, 4);
        if (producer >= kProducers || (int64_t)len + 12 != n ||
            (int)seq != next_seq[producer]) {
          rc = 2;
          done = true;
          break;
        }
        next_seq[producer]++;
        for (uint32_t j = 0; j < len; j++) {
          if (ptr[12 + j] != pattern_byte(producer, seq, j)) {
            rc = 2;
            done = true;
            break;
          }
        }
        frames++;
      }
      if (done) break;
      if (st == -1) done = true;  // EOF and buffer drained
      else if (st == 0) poll(&pfd, 1, 50);
    }
    if (rc == 0 && frames != (long long)kProducers * iters) rc = 2;
    wire_decoder_free(dec);
  }

  for (auto& t : producers) t.join();
  flusher.join();
  wire_writer_free(writer);
  close(fds[0]);
  close(fds[1]);
  return failed.load() ? 2 : rc;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4 || strcmp(argv[1], "threads") != 0) {
    fprintf(stderr,
            "usage: wire_stress threads <workers> <iters_per_producer>\n");
    return 1;
  }
  int workers = atoi(argv[2]);
  int iters = atoi(argv[3]);
  if (workers <= 0 || iters <= 0) return 1;

  // Watchdog: a deadlocked flush/consume pair must fail the run, not
  // hang CI.
  alarm(120);
  signal(SIGALRM, [](int) { _exit(3); });

  std::atomic<int> worst{0};
  std::vector<std::thread> threads;
  for (int i = 0; i < workers; i++) {
    threads.emplace_back([&]() {
      int rc = run_worker(iters);
      int cur = worst.load();
      while (rc > cur && !worst.compare_exchange_weak(cur, rc)) {
      }
    });
  }
  for (auto& t : threads) t.join();
  if (worst.load() != 0) return worst.load();
  printf("STRESS-OK workers=%d iters=%d\n", workers, iters);
  return 0;
}
