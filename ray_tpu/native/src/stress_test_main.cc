// Stress/sanitizer harness for the shm store (SURVEY.md §5.2 parity:
// the reference runs its C++ unit tests under TSan/ASan bazel configs,
// .bazelrc:112-132; this binary is the equivalent seam for the
// daemonless store).
//
// Not compiled into the runtime library: built on demand by
// ray_tpu/native/build.py (plain, -fsanitize=address, or
// -fsanitize=thread) and driven by tests/test_native_stress.py.
//
//   stress_test <threads|procs> <workers> <iters> [arena_mb]
//
// Workers hammer create/seal/get/verify/release/delete concurrently
// over one MAP_SHARED arena. Payloads are filled with a pattern
// derived from the object id, and every reader verifies every byte —
// a torn write, a use-after-free, or an allocator overlap shows up as
// a pattern mismatch (exit 2), a lost wakeup as a watchdog kill
// (exit 3). Thread mode runs under TSan (which is per-process);
// process mode exercises the robust-mutex / cross-process paths.

#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int64_t shm_required_overhead(uint64_t max_objects);
int64_t shm_init(void* base, uint64_t total_size, uint64_t max_objects);
int64_t shm_attach(void* base);
int64_t shm_create(void* base, const uint8_t* id, uint64_t size,
                   uint64_t* offset_out);
int64_t shm_seal(void* base, const uint8_t* id);
int64_t shm_get(void* base, const uint8_t* id, double timeout_s,
                uint64_t* offset_out, uint64_t* size_out);
int64_t shm_release(void* base, const uint8_t* id);
int64_t shm_delete(void* base, const uint8_t* id);
int64_t shm_used_bytes(void* base);
int64_t shm_num_objects(void* base);
}

namespace {

constexpr int kIdLen = 20;  // ObjectID binary length
void* g_base = nullptr;
std::atomic<int> g_failures{0};

void make_id(uint8_t* id, int worker, uint64_t i) {
  memset(id, 0, kIdLen);
  memcpy(id, &worker, sizeof(worker));
  memcpy(id + 8, &i, sizeof(i));
}

uint8_t pattern(const uint8_t* id, uint64_t pos) {
  return (uint8_t)(id[0] * 31 + id[8] * 17 + pos * 7 + 13);
}

void worker_loop(int worker, int n_workers, int iters) {
  uint8_t id[kIdLen];
  unsigned seed = 0x9e3779b9u * (worker + 1);
  for (int i = 0; i < iters; i++) {
    seed = seed * 1664525u + 1013904223u;
    uint64_t size = 64 + (seed % 8192);
    make_id(id, worker, (uint64_t)i);
    uint64_t off = 0;
    int64_t rc = shm_create(g_base, id, size, &off);
    if (rc == -3 /*kFull*/) {
      // arena pressure: retire an old object of ours and retry once
      if (i > 4) {
        uint8_t old_id[kIdLen];
        make_id(old_id, worker, (uint64_t)(i - 4));
        shm_delete(g_base, old_id);
      }
      rc = shm_create(g_base, id, size, &off);
      if (rc != 0) continue;  // still full: skip this round
    } else if (rc != 0) {
      fprintf(stderr, "worker %d: create rc=%ld\n", worker, (long)rc);
      g_failures.fetch_add(1);
      continue;
    }
    uint8_t* payload = (uint8_t*)g_base + off;
    for (uint64_t p = 0; p < size; p++) payload[p] = pattern(id, p);
    if (shm_seal(g_base, id) != 0) {
      fprintf(stderr, "worker %d: seal failed\n", worker);
      g_failures.fetch_add(1);
      continue;
    }

    // read-verify a NEIGHBOR's recent object (cross-worker contention)
    uint8_t other[kIdLen];
    int peer = (worker + 1) % n_workers;
    uint64_t peer_iter = (uint64_t)(i > 2 ? i - 2 : 0);
    make_id(other, peer, peer_iter);
    uint64_t roff = 0, rsize = 0;
    rc = shm_get(g_base, other, 0.05, &roff, &rsize);
    if (rc == 0) {
      const uint8_t* rp = (const uint8_t*)g_base + roff;
      for (uint64_t p = 0; p < rsize; p++) {
        if (rp[p] != pattern(other, p)) {
          fprintf(stderr,
                  "CORRUPTION worker %d: peer %d iter %lu byte %lu "
                  "got %u want %u\n",
                  worker, peer, (unsigned long)peer_iter,
                  (unsigned long)p, rp[p], pattern(other, p));
          g_failures.fetch_add(1);
          break;
        }
      }
      shm_release(g_base, other);
    }

    // churn: retire our object from a few iterations back
    if (i >= 8) {
      make_id(id, worker, (uint64_t)(i - 8));
      shm_delete(g_base, id);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <threads|procs> <workers> <iters> [mb]\n",
            argv[0]);
    return 64;
  }
  const bool use_procs = std::string(argv[1]) == "procs";
  const int n_workers = atoi(argv[2]);
  const int iters = atoi(argv[3]);
  const uint64_t arena_mb = argc > 4 ? (uint64_t)atoll(argv[4]) : 64;

  alarm(120);  // watchdog: a lost wakeup / deadlock kills us (exit 3)
  signal(SIGALRM, [](int) { _exit(3); });

  const uint64_t max_objects = 4096;
  const uint64_t total =
      arena_mb * 1024 * 1024 + (uint64_t)shm_required_overhead(max_objects);
  g_base = mmap(nullptr, total, PROT_READ | PROT_WRITE,
                MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  if (g_base == MAP_FAILED) { perror("mmap"); return 64; }
  if (shm_init(g_base, total, max_objects) != 0) {
    fprintf(stderr, "init failed\n");
    return 64;
  }

  if (use_procs) {
    std::vector<pid_t> pids;
    for (int w = 0; w < n_workers; w++) {
      pid_t pid = fork();
      if (pid == 0) {
        worker_loop(w, n_workers, iters);
        _exit(g_failures.load() ? 2 : 0);
      }
      pids.push_back(pid);
    }
    int bad = 0;
    for (pid_t pid : pids) {
      int status = 0;
      waitpid(pid, &status, 0);
      if (!WIFEXITED(status) || WEXITSTATUS(status) != 0) bad++;
    }
    if (bad) {
      fprintf(stderr, "%d child(ren) failed\n", bad);
      return 2;
    }
  } else {
    std::vector<std::thread> threads;
    for (int w = 0; w < n_workers; w++)
      threads.emplace_back(worker_loop, w, n_workers, iters);
    for (auto& t : threads) t.join();
    if (g_failures.load()) return 2;
  }
  fprintf(stderr, "stress ok: objects=%ld used=%ld\n",
          (long)shm_num_objects(g_base), (long)shm_used_bytes(g_base));
  printf("STRESS-OK\n");
  return 0;
}
