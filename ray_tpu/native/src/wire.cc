// Native wire codec: length-prefix framing off the GIL.
//
// The Python control plane frames every message as a 4-byte
// little-endian length + payload (protocol.py `_LEN`). This module
// moves the per-byte work of that framing — recv into a growable
// buffer, frame boundary parsing, outbound coalescing, and the
// writev/recv syscalls themselves — into plain C++ reached over a
// ctypes ABI (same pattern as shm_store.cc: extern "C", int64 status
// codes, no pybind11). ctypes releases the GIL for the duration of
// every call, so socket syscalls and memcpy no longer serialize
// against Python bytecode on the hot path.
//
// Decoder: single-threaded (owned by the IO loop thread) — no lock.
// Writer: internally locked — any Python thread may enqueue/flush
// concurrently; writev only ever runs on non-blocking fds so holding
// the mutex across the syscall never sleeps.

#include <errno.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <deque>
#include <mutex>
#include <vector>

namespace {

// Frames above this are a protocol error (the u32 prefix caps at 4GB
// anyway; control messages and 1MB object chunks sit far below).
constexpr uint64_t kMaxFrame = 0xF0000000ULL;
// Outbound frames are coalesced into blocks of roughly this size so a
// flush sends one writev over many queued frames.
constexpr size_t kBlock = 256 * 1024;
constexpr int kMaxIov = 64;
constexpr size_t kRecvChunk = 256 * 1024;

constexpr int64_t kOk = 0;
constexpr int64_t kEof = -1;       // clean peer shutdown
constexpr int64_t kConnErr = -2;   // fatal socket error
constexpr int64_t kProtoErr = -3;  // oversize / malformed frame

struct Decoder {
  std::vector<uint8_t> buf;
  size_t start = 0;  // offset of first unconsumed byte
  bool eof = false;
  int64_t error = 0;  // sticky kConnErr / kProtoErr
};

struct Writer {
  std::mutex mu;
  std::deque<std::vector<uint8_t>> blocks;
  size_t head_off = 0;  // bytes of blocks.front() already written
  uint64_t queued = 0;
};

uint32_t read_le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

void write_le32(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)(v & 0xff);
  p[1] = (uint8_t)((v >> 8) & 0xff);
  p[2] = (uint8_t)((v >> 16) & 0xff);
  p[3] = (uint8_t)((v >> 24) & 0xff);
}

void compact(Decoder* d) {
  // Reclaim consumed prefix once it dominates the buffer; cheap
  // amortized memmove instead of shifting on every frame.
  if (d->start == d->buf.size()) {
    d->buf.clear();
    d->start = 0;
  } else if (d->start > (1 << 20) && d->start > d->buf.size() / 2) {
    d->buf.erase(d->buf.begin(), d->buf.begin() + (long)d->start);
    d->start = 0;
  }
}

}  // namespace

extern "C" {

void* wire_decoder_new() { return new Decoder(); }

void wire_decoder_free(void* h) { delete static_cast<Decoder*>(h); }

// Drain the (non-blocking) fd into the internal buffer. Returns bytes
// newly buffered (>= 0; 0 means EAGAIN with nothing new), kEof once
// the peer has shut down, kConnErr on a fatal socket error, kProtoErr
// if a frame header announces an oversize frame. EOF/error are sticky
// but complete frames already buffered stay retrievable via
// wire_decoder_next.
int64_t wire_decoder_read_fd(void* h, int fd) {
  Decoder* d = static_cast<Decoder*>(h);
  if (d->error) return d->error;
  int64_t got = 0;
  for (;;) {
    size_t old = d->buf.size();
    d->buf.resize(old + kRecvChunk);
    ssize_t n = ::recv(fd, d->buf.data() + old, kRecvChunk, 0);
    if (n > 0) {
      d->buf.resize(old + (size_t)n);
      got += n;
      if ((size_t)n < kRecvChunk) break;  // drained the socket buffer
      continue;
    }
    d->buf.resize(old);
    if (n == 0) {
      d->eof = true;
      break;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) break;
    d->error = kConnErr;
    return got > 0 ? got : kConnErr;
  }
  // Early oversize check so a poisoned header fails the connection
  // before we buffer gigabytes chasing it.
  if (d->buf.size() - d->start >= 4) {
    uint32_t len = read_le32(d->buf.data() + d->start);
    if ((uint64_t)len > kMaxFrame) {
      d->error = kProtoErr;
      return kProtoErr;
    }
  }
  if (got == 0 && d->eof) return kEof;
  return got;
}

// Test/handshake seam: inject bytes as if they had been read from the
// socket (used to hand leftover handshake bytes to a fresh decoder).
int64_t wire_decoder_feed(void* h, const uint8_t* data, uint64_t len) {
  Decoder* d = static_cast<Decoder*>(h);
  if (d->error) return d->error;
  d->buf.insert(d->buf.end(), data, data + len);
  return (int64_t)len;
}

// Pop the next complete frame: returns its length and points *out at
// the payload (valid until the next decoder call — the caller copies
// immediately). Returns kEof when no complete frame is buffered,
// kProtoErr on an oversize header.
int64_t wire_decoder_next(void* h, const uint8_t** out) {
  Decoder* d = static_cast<Decoder*>(h);
  size_t avail = d->buf.size() - d->start;
  if (avail < 4) {
    compact(d);
    return kEof;
  }
  uint32_t len = read_le32(d->buf.data() + d->start);
  if ((uint64_t)len > kMaxFrame) {
    d->error = kProtoErr;
    return kProtoErr;
  }
  if (avail < 4 + (uint64_t)len) {
    compact(d);
    return kEof;
  }
  *out = d->buf.data() + d->start + 4;
  d->start += 4 + (size_t)len;
  return (int64_t)len;
}

// Unconsumed raw bytes (partial frame tail) — used when a connection
// is detached from the loop (CAPI handoff) so no bytes are lost.
int64_t wire_decoder_leftover(void* h, const uint8_t** out) {
  Decoder* d = static_cast<Decoder*>(h);
  *out = d->buf.data() + d->start;
  return (int64_t)(d->buf.size() - d->start);
}

int64_t wire_decoder_buffered(void* h) {
  Decoder* d = static_cast<Decoder*>(h);
  return (int64_t)(d->buf.size() - d->start);
}

void* wire_writer_new() { return new Writer(); }

void wire_writer_free(void* h) { delete static_cast<Writer*>(h); }

// Queue one frame (4-byte LE length prefix + payload) for sending.
// Frames are coalesced into ~256KB blocks so one flush writev covers
// many frames. Thread-safe. Returns total queued bytes after the
// enqueue.
int64_t wire_writer_enqueue(void* h, const uint8_t* data, uint64_t len) {
  if (len > kMaxFrame) return kProtoErr;
  Writer* w = static_cast<Writer*>(h);
  std::lock_guard<std::mutex> g(w->mu);
  size_t need = 4 + (size_t)len;
  bool fresh = w->blocks.empty() ||
               w->blocks.back().size() + need > kBlock;
  if (fresh) {
    w->blocks.emplace_back();
    w->blocks.back().reserve(need > kBlock ? need : kBlock);
  }
  std::vector<uint8_t>& blk = w->blocks.back();
  size_t at = blk.size();
  blk.resize(at + need);
  write_le32(blk.data() + at, (uint32_t)len);
  memcpy(blk.data() + at + 4, data, (size_t)len);
  w->queued += need;
  return (int64_t)w->queued;
}

// Flush queued blocks to the (non-blocking) fd via writev. Returns the
// number of bytes still queued (0 = fully flushed) or kConnErr on a
// fatal socket error. Safe to call from any thread; concurrent
// flushers serialize on the internal mutex.
int64_t wire_writer_flush_fd(void* h, int fd) {
  Writer* w = static_cast<Writer*>(h);
  std::lock_guard<std::mutex> g(w->mu);
  while (!w->blocks.empty()) {
    struct iovec iov[kMaxIov];
    int cnt = 0;
    size_t off = w->head_off;
    for (auto& blk : w->blocks) {
      iov[cnt].iov_base = blk.data() + off;
      iov[cnt].iov_len = blk.size() - off;
      off = 0;
      if (++cnt == kMaxIov) break;
    }
    ssize_t n = ::writev(fd, iov, cnt);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        return (int64_t)w->queued;
      return kConnErr;
    }
    w->queued -= (uint64_t)n;
    size_t left = (size_t)n;
    while (left > 0) {
      std::vector<uint8_t>& front = w->blocks.front();
      size_t remain = front.size() - w->head_off;
      if (left >= remain) {
        left -= remain;
        w->head_off = 0;
        w->blocks.pop_front();
      } else {
        w->head_off += left;
        left = 0;
      }
    }
  }
  return (int64_t)w->queued;
}

int64_t wire_writer_queued(void* h) {
  Writer* w = static_cast<Writer*>(h);
  std::lock_guard<std::mutex> g(w->mu);
  return (int64_t)w->queued;
}

}  // extern "C"
