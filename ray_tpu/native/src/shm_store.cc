// Node-local shared-memory object store.
//
// Capability parity with the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55,
// plasma_allocator.cc, obj_lifecycle_mgr.cc): create/seal/get/release/
// delete of immutable binary objects in a shared-memory arena mapped by
// every worker process on the node, with blocking get (waits for seal),
// reference counts pinning objects, and LRU eviction of unreferenced
// sealed objects under memory pressure (reference: eviction_policy.cc).
//
// Unlike plasma there is no store daemon or unix-socket protocol: the
// arena itself carries a process-shared robust mutex + condvar, and every
// process operates on the shared state directly through this library.
// That removes a context switch + fd-passing round trip from the object
// hot path (reference: protocol.cc, fling.cc) — on a TPU host the store
// is purely a staging area between Python workers, the data loader, and
// device transfer, so the daemonless design is both simpler and faster.
//
// Layout (all offsets relative to arena base; data 64-byte aligned):
//   [Header | ObjectEntry x max_objects | data region (blocks)]

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <time.h>

#include <cstdio>

namespace {

constexpr uint64_t kMagic = 0x7470755f73746f72ULL;  // "tpu_stor"
constexpr uint64_t kAlign = 64;
constexpr uint64_t kBlockHeader = 64;  // keeps payloads 64B-aligned

enum State : uint8_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
};

enum Err : int64_t {
  kOk = 0,
  kNotFound = -1,
  kExists = -2,
  kFull = -3,
  kTimeout = -4,
  kCorrupt = -5,
  kBadState = -6,
};

struct ObjectEntry {
  uint8_t id[16];
  uint8_t state;
  uint8_t pad[7];
  uint64_t offset;  // payload offset from arena base
  uint64_t size;    // payload size
  int64_t refcount;
  uint64_t lru;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t max_objects;
  uint64_t entries_offset;
  uint64_t data_offset;
  uint64_t free_head;  // offset of first free block, 0 = none
  uint64_t lru_tick;
  uint64_t used_bytes;
  uint64_t num_objects;
  pthread_mutex_t mutex;
  pthread_cond_t cond;
};

// A block in the data region. When free, `next` links the sorted-by-offset
// free list; when allocated, the payload starts at offset + kBlockHeader.
struct Block {
  uint64_t size;  // total block size including header
  uint64_t next;  // next free block offset (0 = end)
};

inline Header* H(void* base) { return reinterpret_cast<Header*>(base); }
inline Block* B(void* base, uint64_t off) {
  return reinterpret_cast<Block*>(static_cast<char*>(base) + off);
}
inline ObjectEntry* entries(void* base) {
  return reinterpret_cast<ObjectEntry*>(static_cast<char*>(base) +
                                        H(base)->entries_offset);
}

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

// Robust lock: recover consistency if a holder died mid-critical-section.
int lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

ObjectEntry* find(void* base, const uint8_t* id) {
  Header* h = H(base);
  ObjectEntry* es = entries(base);
  for (uint64_t i = 0; i < h->max_objects; ++i) {
    if (es[i].state != kEmpty && memcmp(es[i].id, id, 16) == 0) return &es[i];
  }
  return nullptr;
}

ObjectEntry* find_slot(void* base) {
  Header* h = H(base);
  ObjectEntry* es = entries(base);
  for (uint64_t i = 0; i < h->max_objects; ++i) {
    if (es[i].state == kEmpty) return &es[i];
  }
  return nullptr;
}

// First-fit allocation from the sorted free list; splits blocks.
uint64_t alloc_block(void* base, uint64_t payload) {
  Header* h = H(base);
  uint64_t need = align_up(payload) + kBlockHeader;
  uint64_t prev = 0;
  uint64_t cur = h->free_head;
  while (cur) {
    Block* b = B(base, cur);
    if (b->size >= need) {
      uint64_t remainder = b->size - need;
      if (remainder >= kBlockHeader + kAlign) {
        // Split: tail remains free.
        uint64_t tail = cur + need;
        Block* t = B(base, tail);
        t->size = remainder;
        t->next = b->next;
        b->size = need;
        if (prev) B(base, prev)->next = tail; else h->free_head = tail;
      } else {
        if (prev) B(base, prev)->next = b->next; else h->free_head = b->next;
      }
      h->used_bytes += b->size;
      return cur;
    }
    prev = cur;
    cur = b->next;
  }
  return 0;
}

// Free with coalescing of adjacent blocks (free list kept sorted by offset).
void free_block(void* base, uint64_t off) {
  Header* h = H(base);
  Block* b = B(base, off);
  h->used_bytes -= b->size;
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = B(base, cur)->next;
  }
  b->next = cur;
  if (prev) B(base, prev)->next = off; else h->free_head = off;
  // Coalesce with next.
  if (cur && off + b->size == cur) {
    b->size += B(base, cur)->size;
    b->next = B(base, cur)->next;
  }
  // Coalesce with prev.
  if (prev && prev + B(base, prev)->size == off) {
    Block* p = B(base, prev);
    p->size += b->size;
    p->next = b->next;
  }
}

// Evict sealed, unreferenced objects in LRU order until `bytes` are free
// or nothing evictable remains. Returns bytes freed. Caller holds lock.
uint64_t evict_locked(void* base, uint64_t bytes) {
  Header* h = H(base);
  ObjectEntry* es = entries(base);
  uint64_t freed = 0;
  while (freed < bytes) {
    ObjectEntry* victim = nullptr;
    for (uint64_t i = 0; i < h->max_objects; ++i) {
      ObjectEntry* e = &es[i];
      if (e->state == kSealed && e->refcount == 0 &&
          (!victim || e->lru < victim->lru)) {
        victim = e;
      }
    }
    if (!victim) break;
    freed += align_up(victim->size) + kBlockHeader;
    free_block(base, victim->offset - kBlockHeader);
    victim->state = kEmpty;
    h->num_objects--;
  }
  return freed;
}

}  // namespace

extern "C" {

int64_t shm_required_overhead(uint64_t max_objects) {
  return align_up(sizeof(Header)) + align_up(max_objects * sizeof(ObjectEntry));
}

int64_t shm_init(void* base, uint64_t total_size, uint64_t max_objects) {
  memset(base, 0, shm_required_overhead(max_objects));
  Header* h = H(base);
  h->total_size = total_size;
  h->max_objects = max_objects;
  h->entries_offset = align_up(sizeof(Header));
  h->data_offset = align_up(h->entries_offset + max_objects * sizeof(ObjectEntry));
  if (h->data_offset + kBlockHeader + kAlign > total_size) return kFull;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->cond, &ca);

  Block* first = B(base, h->data_offset);
  first->size = total_size - h->data_offset;
  first->next = 0;
  h->free_head = h->data_offset;
  h->magic = kMagic;
  return kOk;
}

int64_t shm_attach(void* base) {
  return H(base)->magic == kMagic ? kOk : kCorrupt;
}

// Create an unsealed object and return the payload offset; the caller
// writes the payload then calls shm_seal. Evicts LRU objects if needed.
int64_t shm_create(void* base, const uint8_t* id, uint64_t size,
                   uint64_t* offset_out) {
  Header* h = H(base);
  lock(h);
  if (find(base, id)) {
    pthread_mutex_unlock(&h->mutex);
    return kExists;
  }
  ObjectEntry* slot = find_slot(base);
  if (!slot) {
    pthread_mutex_unlock(&h->mutex);
    return kFull;
  }
  uint64_t block = alloc_block(base, size);
  if (!block) {
    evict_locked(base, align_up(size) + kBlockHeader);
    block = alloc_block(base, size);
  }
  if (!block) {
    pthread_mutex_unlock(&h->mutex);
    return kFull;
  }
  memcpy(slot->id, id, 16);
  slot->state = kCreated;
  slot->offset = block + kBlockHeader;
  slot->size = size;
  slot->refcount = 1;  // creator holds a reference until seal+release
  slot->lru = ++h->lru_tick;
  h->num_objects++;
  *offset_out = slot->offset;
  pthread_mutex_unlock(&h->mutex);
  return kOk;
}

int64_t shm_seal(void* base, const uint8_t* id) {
  Header* h = H(base);
  lock(h);
  ObjectEntry* e = find(base, id);
  if (!e) { pthread_mutex_unlock(&h->mutex); return kNotFound; }
  if (e->state != kCreated) { pthread_mutex_unlock(&h->mutex); return kBadState; }
  e->state = kSealed;
  // The creator reference is kept: it represents the owner's
  // (distributed) reference count and is dropped by shm_delete, so LRU
  // eviction can never reclaim an object whose ObjectRefs are alive
  // (plasma parity: referenced objects are pinned; only deleted /
  // released ones are eviction fodder).
  pthread_cond_broadcast(&h->cond);
  pthread_mutex_unlock(&h->mutex);
  return kOk;
}

// Blocking get: waits until the object is sealed (or timeout), pins it
// with a reference, and returns its payload offset + size.
int64_t shm_get(void* base, const uint8_t* id, double timeout_s,
                uint64_t* offset_out, uint64_t* size_out) {
  Header* h = H(base);
  struct timespec deadline;
  clock_gettime(CLOCK_MONOTONIC, &deadline);
  deadline.tv_sec += (time_t)timeout_s;
  deadline.tv_nsec += (long)((timeout_s - (time_t)timeout_s) * 1e9);
  if (deadline.tv_nsec >= 1000000000L) {
    deadline.tv_sec += 1;
    deadline.tv_nsec -= 1000000000L;
  }
  lock(h);
  for (;;) {
    ObjectEntry* e = find(base, id);
    if (e && e->state == kSealed) {
      e->refcount++;
      e->lru = ++h->lru_tick;
      *offset_out = e->offset;
      *size_out = e->size;
      pthread_mutex_unlock(&h->mutex);
      return kOk;
    }
    if (timeout_s <= 0) {
      pthread_mutex_unlock(&h->mutex);
      return e ? kBadState : kNotFound;
    }
    int rc = pthread_cond_timedwait(&h->cond, &h->mutex, &deadline);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return kTimeout;
    }
  }
}

int64_t shm_contains(void* base, const uint8_t* id) {
  Header* h = H(base);
  lock(h);
  ObjectEntry* e = find(base, id);
  int64_t r = (e && e->state == kSealed) ? 1 : 0;
  pthread_mutex_unlock(&h->mutex);
  return r;
}

int64_t shm_release(void* base, const uint8_t* id) {
  Header* h = H(base);
  lock(h);
  ObjectEntry* e = find(base, id);
  if (!e) { pthread_mutex_unlock(&h->mutex); return kNotFound; }
  if (e->refcount > 0) e->refcount--;
  pthread_mutex_unlock(&h->mutex);
  return kOk;
}

// Delete an object (the owner's distributed refcount hit zero): drops
// the creator pin. Frees immediately unless readers still pin it, in
// which case it becomes prime eviction fodder once they release.
int64_t shm_delete(void* base, const uint8_t* id) {
  Header* h = H(base);
  lock(h);
  ObjectEntry* e = find(base, id);
  if (!e) { pthread_mutex_unlock(&h->mutex); return kNotFound; }
  if (e->refcount > 0) e->refcount--;  // creator pin
  if (e->refcount <= 0) {
    free_block(base, e->offset - kBlockHeader);
    e->state = kEmpty;
    h->num_objects--;
  } else {
    e->lru = 0;
  }
  pthread_mutex_unlock(&h->mutex);
  return kOk;
}

// refsan eviction canary: shm_delete, except that when the slot is
// actually freed (no reader pins outstanding) the payload range is
// first filled with `poison` — still under the store lock, so a
// concurrent shm_create in another process cannot reuse the block
// between the free and the poison write. A dangling zero-copy view
// left behind by a buggy early-release path then reads a deterministic
// canary pattern instead of stale-or-reused bytes.
int64_t shm_delete_poison(void* base, const uint8_t* id, int64_t poison) {
  Header* h = H(base);
  lock(h);
  ObjectEntry* e = find(base, id);
  if (!e) { pthread_mutex_unlock(&h->mutex); return kNotFound; }
  if (e->refcount > 0) e->refcount--;  // creator pin
  if (e->refcount <= 0) {
    memset((char*)base + e->offset, (int)poison, e->size);
    free_block(base, e->offset - kBlockHeader);
    e->state = kEmpty;
    h->num_objects--;
  } else {
    e->lru = 0;
  }
  pthread_mutex_unlock(&h->mutex);
  return kOk;
}

int64_t shm_evict(void* base, uint64_t bytes) {
  Header* h = H(base);
  lock(h);
  uint64_t freed = evict_locked(base, bytes);
  pthread_mutex_unlock(&h->mutex);
  return (int64_t)freed;
}

int64_t shm_used_bytes(void* base) { return (int64_t)H(base)->used_bytes; }
int64_t shm_num_objects(void* base) { return (int64_t)H(base)->num_objects; }
int64_t shm_total_bytes(void* base) {
  return (int64_t)(H(base)->total_size - H(base)->data_offset);
}

}  // extern "C"
