"""ctypes bindings for the native library."""

from __future__ import annotations

import ctypes

from ray_tpu.native.build import ensure_built

_lib = None


def load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        u64 = ctypes.c_uint64
        i64 = ctypes.c_int64
        p = ctypes.c_void_p
        idp = ctypes.c_char_p
        lib.shm_required_overhead.restype = i64
        lib.shm_required_overhead.argtypes = [u64]
        lib.shm_init.restype = i64
        lib.shm_init.argtypes = [p, u64, u64]
        lib.shm_attach.restype = i64
        lib.shm_attach.argtypes = [p]
        lib.shm_create.restype = i64
        lib.shm_create.argtypes = [p, idp, u64, ctypes.POINTER(u64)]
        lib.shm_seal.restype = i64
        lib.shm_seal.argtypes = [p, idp]
        lib.shm_get.restype = i64
        lib.shm_get.argtypes = [p, idp, ctypes.c_double, ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib.shm_contains.restype = i64
        lib.shm_contains.argtypes = [p, idp]
        lib.shm_release.restype = i64
        lib.shm_release.argtypes = [p, idp]
        lib.shm_delete.restype = i64
        lib.shm_delete.argtypes = [p, idp]
        lib.shm_evict.restype = i64
        lib.shm_evict.argtypes = [p, u64]
        lib.shm_used_bytes.restype = i64
        lib.shm_used_bytes.argtypes = [p]
        lib.shm_num_objects.restype = i64
        lib.shm_num_objects.argtypes = [p]
        lib.shm_total_bytes.restype = i64
        lib.shm_total_bytes.argtypes = [p]
        _lib = lib
    return _lib


OK = 0
NOT_FOUND = -1
EXISTS = -2
FULL = -3
TIMEOUT = -4
CORRUPT = -5
BAD_STATE = -6
