"""ctypes bindings for the native library."""

from __future__ import annotations

import ctypes

from ray_tpu.native.build import NativeBuildError, ensure_built

_lib = None
_load_error: Exception | None = None


def load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        u64 = ctypes.c_uint64
        i64 = ctypes.c_int64
        p = ctypes.c_void_p
        idp = ctypes.c_char_p
        lib.shm_required_overhead.restype = i64
        lib.shm_required_overhead.argtypes = [u64]
        lib.shm_init.restype = i64
        lib.shm_init.argtypes = [p, u64, u64]
        lib.shm_attach.restype = i64
        lib.shm_attach.argtypes = [p]
        lib.shm_create.restype = i64
        lib.shm_create.argtypes = [p, idp, u64, ctypes.POINTER(u64)]
        lib.shm_seal.restype = i64
        lib.shm_seal.argtypes = [p, idp]
        lib.shm_get.restype = i64
        lib.shm_get.argtypes = [p, idp, ctypes.c_double, ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib.shm_contains.restype = i64
        lib.shm_contains.argtypes = [p, idp]
        lib.shm_release.restype = i64
        lib.shm_release.argtypes = [p, idp]
        lib.shm_delete.restype = i64
        lib.shm_delete.argtypes = [p, idp]
        lib.shm_delete_poison.restype = i64
        lib.shm_delete_poison.argtypes = [p, idp, i64]
        lib.shm_evict.restype = i64
        lib.shm_evict.argtypes = [p, u64]
        lib.shm_used_bytes.restype = i64
        lib.shm_used_bytes.argtypes = [p]
        lib.shm_num_objects.restype = i64
        lib.shm_num_objects.argtypes = [p]
        lib.shm_total_bytes.restype = i64
        lib.shm_total_bytes.argtypes = [p]
        # --- wire codec (wire.cc) ---
        pp = ctypes.POINTER(p)
        lib.wire_decoder_new.restype = p
        lib.wire_decoder_new.argtypes = []
        lib.wire_decoder_free.restype = None
        lib.wire_decoder_free.argtypes = [p]
        lib.wire_decoder_read_fd.restype = i64
        lib.wire_decoder_read_fd.argtypes = [p, ctypes.c_int]
        lib.wire_decoder_feed.restype = i64
        lib.wire_decoder_feed.argtypes = [p, ctypes.c_char_p, u64]
        lib.wire_decoder_next.restype = i64
        lib.wire_decoder_next.argtypes = [p, pp]
        lib.wire_decoder_leftover.restype = i64
        lib.wire_decoder_leftover.argtypes = [p, pp]
        lib.wire_decoder_buffered.restype = i64
        lib.wire_decoder_buffered.argtypes = [p]
        lib.wire_writer_new.restype = p
        lib.wire_writer_new.argtypes = []
        lib.wire_writer_free.restype = None
        lib.wire_writer_free.argtypes = [p]
        lib.wire_writer_enqueue.restype = i64
        lib.wire_writer_enqueue.argtypes = [p, ctypes.c_char_p, u64]
        lib.wire_writer_flush_fd.restype = i64
        lib.wire_writer_flush_fd.argtypes = [p, ctypes.c_int]
        lib.wire_writer_queued.restype = i64
        lib.wire_writer_queued.argtypes = [p]
        _lib = lib
    return _lib


def try_load():
    """load(), or None when the native toolchain/library is
    unavailable (callers use their pure-Python fallback). The failure
    is cached so this is cheap to call on hot setup paths."""
    global _load_error
    if _load_error is not None:
        return None
    try:
        return load()
    except (NativeBuildError, OSError) as exc:
        _load_error = exc
        return None


OK = 0
NOT_FOUND = -1
EXISTS = -2
FULL = -3
TIMEOUT = -4
CORRUPT = -5
BAD_STATE = -6

# wire codec status codes
WIRE_EOF = -1
WIRE_ERR = -2
WIRE_PROTO = -3
