"""recovery: per-incident MTTR timelines from the cluster event plane.

Answers "what died, when did we notice, and how long did recovery
take" by folding the lifecycle events (``core/events.py``) into
incidents: each death event (``NODE_DEAD`` / ``WORKER_EXIT`` /
``ACTOR_DEAD``) roots a causal chain — the retries, lease grants,
actor restarts and lineage reconstructions that carry its seq in
``caused_by`` — and the fold extracts the recovery phases:

* **detect**      — last heartbeat → declared dead (stamped on the
  NODE_DEAD event by ``gcs.mark_node_dead``),
* **reschedule**  — death → the caused lease grant landing the retried
  work on a surviving node,
* **reconstruct** — lineage re-execution span of each lost object,
* **MTTR**        — detect + (last chained event − death).

PR-12 flight journals, when the recorder is on, are correlated by time
window so the report shows what each process was doing around the
incident. The incident tail is attached to ``ActorDiedError`` /
``DAGExecutionError`` the same way the flight recorder attaches
journal tails.

Usage::

    ray_tpu.devtools.recovery.recovery_report()   # live, dict
    print(recovery.render(recovery.recovery_report()))
    python -m ray_tpu.devtools.recovery [--json] [state.json]
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.events import DEATH_KINDS

#: chain lines included in exception-attached incident tails
TAIL_EVENTS = 12


def _as_dicts(events) -> List[Dict[str, Any]]:
    return [ev.to_dict() if hasattr(ev, "to_dict") else dict(ev)
            for ev in events]


def _live_events(limit: int = 100_000) -> Optional[List[Dict[str, Any]]]:
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is None or not getattr(rt, "is_driver", False):
        return None
    return _as_dicts(rt.gcs.list_cluster_events(limit=limit))


def _snapshot_events() -> List[Dict[str, Any]]:
    """Events from the last session's state.json (the out-of-process
    path the CLI uses)."""
    import os
    import tempfile
    pointer = os.path.join(tempfile.gettempdir(),
                           "ray_tpu_last_session.json")
    with open(pointer) as f:
        state_path = json.load(f)["state_path"]
    with open(state_path) as f:
        return json.load(f).get("events", [])


def _entity(ev: Dict[str, Any]) -> str:
    for key in ("node_id", "actor_id", "worker_id", "task_id"):
        if ev.get(key):
            return f"{key.split('_')[0]}={ev[key][:12]}"
    return ""


def recovery_report(events=None, journals=None) -> Dict[str, Any]:
    """Fold lifecycle events (+ flight journals) into per-incident
    recovery timelines. ``events``: ClusterEvent objects or dicts;
    None reads the live GCS store. ``journals``: label -> aligned
    event tuples (``flight_recorder.merged_journals()`` shape); None
    reads the live recorder; pass ``{}`` to skip correlation."""
    if events is None:
        events = _live_events() or []
    events = _as_dicts(events)
    by_seq = {ev["seq"]: ev for ev in events}
    children: Dict[int, List[dict]] = {}
    counts: Dict[str, int] = {}
    for ev in events:
        counts[ev["kind"]] = counts.get(ev["kind"], 0) + 1
        if ev.get("caused_by") is not None:
            children.setdefault(ev["caused_by"], []).append(ev)

    incidents: List[Dict[str, Any]] = []
    for root in events:
        if root["kind"] not in DEATH_KINDS:
            continue
        parent = by_seq.get(root.get("caused_by"))
        if parent is not None and parent["kind"] in DEATH_KINDS:
            continue  # chained death: belongs to the parent's incident
        chain: List[dict] = []
        seen: set = set()
        stack = [root]
        while stack:
            cur = stack.pop()
            if cur["seq"] in seen:
                continue
            seen.add(cur["seq"])
            chain.append(cur)
            stack.extend(children.get(cur["seq"], ()))
        if root["severity"] == "DEBUG" and len(chain) == 1:
            continue  # idle worker reclaim: no recovery rooted here
        chain.sort(key=lambda e: (e["timestamp"], e["seq"]))
        data = root.get("data") or {}
        detect_s = float(data.get("detect_s") or 0.0)
        reschedule_s = max(
            (float((e.get("data") or {}).get("reschedule_s") or 0.0)
             for e in chain if e["kind"] == "LEASE_GRANTED"),
            default=0.0)
        reconstruct_s = max(
            (float((e.get("data") or {}).get("reconstruct_s") or 0.0)
             for e in chain if e["kind"] == "RECONSTRUCT_DONE"),
            default=0.0)
        last_ts = chain[-1]["timestamp"]
        mttr_s = detect_s + max(0.0, last_ts - root["timestamp"])

        def _ids(key: str) -> List[str]:
            return sorted({e[key] for e in chain if e.get(key)})

        incidents.append({
            "root_seq": root["seq"],
            "root_kind": root["kind"],
            "root_ts": root["timestamp"],
            "severity": root["severity"],
            "entity": _entity(root),
            "precursor": (None if parent is None else
                          {"seq": parent["seq"], "kind": parent["kind"],
                           "message": parent.get("message", "")}),
            "detect_s": round(detect_s, 6),
            "reschedule_s": round(reschedule_s, 6),
            "reconstruct_s": round(reconstruct_s, 6),
            "mttr_s": round(mttr_s, 6),
            "affected": {
                "tasks": _ids("task_id"),
                "actors": _ids("actor_id"),
                "workers": _ids("worker_id"),
                "nodes": _ids("node_id"),
                "objects": sorted({(e.get("data") or {}).get("oid")
                                   for e in chain
                                   if (e.get("data") or {}).get("oid")}),
            },
            "chain": chain,
            "journal": _correlate_journals(
                journals, root["timestamp"] - detect_s - 0.5,
                last_ts + 0.5),
        })
    incidents.sort(key=lambda inc: inc["root_ts"])
    collsan_findings = _collsan_findings()
    _attach_collsan(incidents, collsan_findings)
    return {"generated_at": time.time(),
            "events_scanned": len(events),
            "counts": counts,
            "collsan": collsan_findings,
            "incidents": incidents}


def _collsan_findings() -> List[Dict[str, Any]]:
    """Current collsan findings (cross-rank mismatches + stalled
    collectives). Best-effort: empty when collsan is off or broken."""
    try:
        from ray_tpu.devtools import collsan
        return collsan.report()
    except Exception:  # noqa: BLE001 — correlation is best-effort
        return []


def _attach_collsan(incidents: List[Dict[str, Any]],
                    findings: List[Dict[str, Any]]) -> None:
    """Chain stalled-collective findings onto the node death that
    parked them: a stall whose ranks parked within a stall-window of a
    NODE_DEAD root is that incident's symptom (the dead member never
    arrived, so the survivors wait forever inside the collective)."""
    if not findings:
        return
    dead = [inc for inc in incidents if inc["root_kind"] == "NODE_DEAD"]
    if not dead:
        return
    from ray_tpu.devtools import collsan
    window = collsan.stall_threshold_s() + 30.0
    for finding in findings:
        parked = finding.get("parked_since")
        if parked is None:
            continue
        inc = min(dead, key=lambda i: abs(parked - i["root_ts"]))
        if abs(parked - inc["root_ts"]) <= window:
            inc.setdefault("collsan", []).append(finding)


def _correlate_journals(journals, t_lo: float, t_hi: float
                        ) -> Dict[str, List[str]]:
    """Flight-journal lines overlapping the incident window [t_lo,
    t_hi] (wall-clock seconds), per label — what each process was
    doing around the death. Best-effort: empty on any trouble."""
    try:
        from ray_tpu.util import flight_recorder
        if journals is None:
            journals = flight_recorder.merged_journals()
        if not journals:
            return {}
        anchor_wall, anchor_ns = flight_recorder._get_anchor()
        lo_ns = anchor_ns + int((t_lo - anchor_wall) * 1e9)
        hi_ns = anchor_ns + int((t_hi - anchor_wall) * 1e9)
        out: Dict[str, List[str]] = {}
        for label, evs in journals.items():
            window = [ev for ev in evs
                      if lo_ns <= ev[1] + ev[2] and ev[1] <= hi_ns]
            if window:
                out[label] = flight_recorder.format_events(
                    window[-flight_recorder.TAIL_EVENTS:])
        return out
    except Exception:  # noqa: BLE001 — correlation is best-effort
        return {}


def _chain_lines(inc: Dict[str, Any],
                 limit: int = TAIL_EVENTS) -> List[str]:
    t0 = inc["root_ts"]
    lines = []
    for ev in inc["chain"][:limit]:
        line = (f"+{ev['timestamp'] - t0:7.3f}s #{ev['seq']} "
                f"{ev['kind']}")
        ent = _entity(ev)
        if ent:
            line += f" {ent}"
        if ev.get("message"):
            line += f" — {ev['message']}"
        lines.append(line)
    dropped = len(inc["chain"]) - limit
    if dropped > 0:
        lines.append(f"... {dropped} more chained events")
    return lines


def render(report: Dict[str, Any]) -> str:
    lines = ["recovery report (cluster event plane)"]
    lines.append(f"  events scanned: {report['events_scanned']}  "
                 f"incidents: {len(report['incidents'])}")
    for n, inc in enumerate(report["incidents"], 1):
        lines.append(
            f"  incident {n}: {inc['root_kind']} {inc['entity']} "
            f"(event #{inc['root_seq']}, {inc['severity']})")
        if inc.get("precursor"):
            pre = inc["precursor"]
            lines.append(f"    precursor: #{pre['seq']} {pre['kind']} "
                         f"{pre['message']}")
        lines.append(
            f"    detect {inc['detect_s']:.3f}s  "
            f"reschedule {inc['reschedule_s']:.3f}s  "
            f"reconstruct {inc['reconstruct_s']:.3f}s  "
            f"MTTR {inc['mttr_s']:.3f}s")
        aff = inc["affected"]
        lines.append(
            f"    affected: {len(aff['tasks'])} tasks, "
            f"{len(aff['actors'])} actors, "
            f"{len(aff['objects'])} objects, "
            f"{len(aff['workers'])} workers")
        for f in inc.get("collsan", ()):
            lines.append("    collsan: "
                         + (f.get("detail") or f.get("kind", "finding")))
        lines.append("    chain:")
        for line in _chain_lines(inc, limit=40):
            lines.append("      " + line)
        for label, jlines in (inc.get("journal") or {}).items():
            lines.append(f"    journal {label}:")
            for jline in jlines:
                lines.append("      " + jline)
    return "\n".join(lines)


def _tail(inc: Dict[str, Any]) -> str:
    lines = _chain_lines(inc)
    return (f"\n  recovery timeline (incident #{inc['root_seq']} "
            f"{inc['root_kind']}, MTTR {inc['mttr_s']:.3f}s):\n    "
            + "\n    ".join(lines))


def incident_tail_text(seq: Optional[int]) -> str:
    """Compact incident timeline for attaching to an exception message
    (the ActorDiedError path), located by any event seq in the chain.
    Empty string when events are off or anything goes wrong."""
    if seq is None:
        return ""
    try:
        events = _live_events()
        if not events:
            return ""
        report = recovery_report(events=events, journals={})
        for inc in report["incidents"]:
            if any(e["seq"] == seq for e in inc["chain"]):
                return _tail(inc)
    except Exception:  # graftlint: disable=GL004
        pass  # best-effort decoration: never worsen a death report
    return ""


def recent_incident_text(window_s: float = 30.0) -> str:
    """Tail of the most recent incident rooted within ``window_s`` —
    the DAGExecutionError attachment (a DAG failure can't name the
    event seq that killed it, but the timing does)."""
    try:
        events = _live_events()
        if not events:
            return ""
        report = recovery_report(events=events, journals={})
        cutoff = time.time() - window_s
        recent = [inc for inc in report["incidents"]
                  if inc["root_ts"] >= cutoff]
        if recent:
            return _tail(recent[-1])
    except Exception:  # graftlint: disable=GL004
        pass  # best-effort decoration: never worsen a death report
    return ""


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("--")]
    events = None
    if paths:
        with open(paths[0]) as f:
            payload = json.load(f)
        events = (payload.get("events", payload)
                  if isinstance(payload, dict) else payload)
    else:
        events = _live_events()
        if events is None:
            try:
                events = _snapshot_events()
            except (OSError, KeyError, ValueError):
                print("no live driver and no session snapshot found; "
                      "pass a state.json path", file=sys.stderr)
                return 2
    report = recovery_report(events=events,
                             journals=None if not paths else {})
    if as_json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
