"""graftlint: an AST rule engine for ray_tpu's thread-based control
plane.

The control plane guards its shared state with ~70 ``threading.Lock``
sites; at production scale the bottleneck is silent races and
deadlocks, not throughput (Podracer, arXiv:2104.06272; MPMD pipeline
schedulers, arXiv:2412.14374). Generic linters can't see framework
conventions — which classes own locks, what a TaskSpec must carry,
what a metric must be named — so this engine ships framework-specific
rules and grows with the codebase.

Usage::

    python -m ray_tpu.devtools.lint [paths...]
    python -m ray_tpu.devtools.lint ray_tpu/ --write-baseline

Findings are suppressed three ways:

* per-line: a ``# graftlint: disable=GL004`` comment on the reported
  line (comma-separate several ids; ``disable=all`` kills every rule);
* baseline: a checked-in ``graftlint_baseline.json`` grandfathers
  existing findings by (file, rule, enclosing scope) — line drift
  does not invalidate it; NEW findings in a scope still fail;
* ``--select``/``--ignore`` on the command line.

Rules are plain classes in a registry; add one by subclassing
``Rule`` and decorating with ``@register``.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

BASELINE_DEFAULT = "graftlint_baseline.json"

# ---------------------------------------------------------------------
# findings


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str   # posix-style, relative to the scan root when possible
    line: int
    col: int
    message: str
    scope: str  # enclosing "Class.method" qualname ("<module>" at top)

    @property
    def key(self) -> str:
        """Baseline fingerprint: stable across line-number drift."""
        return f"{self.path}::{self.rule}::{self.scope}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


# ---------------------------------------------------------------------
# rule registry

RULES: "Dict[str, Rule]" = {}


def register(cls):
    inst = cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


class Rule:
    id: str = ""
    name: str = ""
    rationale: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------
# per-file context: one parse + one annotation pass shared by all rules

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_EVENT_FACTORIES = {"Condition", "Event"}
_LOCKISH_NAME = re.compile(r"(?:^|_)(?:lock|mutex|cv|cond)(?:$|_)|lock$")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:#|$)")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class FileContext:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = self._parse_suppressions()
        self._annotate()

    # -- suppression comments -----------------------------------------
    def _parse_suppressions(self) -> Dict[int, set]:
        out: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            if "graftlint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {s.strip().upper() for s in m.group(1).split(",")
                       if s.strip()}
                out[i] = ids
        return out

    def suppressed(self, finding: Finding) -> bool:
        ids = self.suppressions.get(finding.line)
        return bool(ids) and (finding.rule in ids or "ALL" in ids)

    # -- annotation pass ----------------------------------------------
    def _annotate(self) -> None:
        """Attach to every node: ``_gl_scope`` (Class.method qualname),
        ``_gl_func`` (innermost function name or None), ``_gl_class``
        (innermost ClassDef node or None), ``_gl_lockdepth`` (number of
        enclosing ``with <lock>`` blocks). ClassDef nodes additionally
        get ``_gl_locks`` / ``_gl_events`` (self-attribute names bound
        to Lock/RLock/Condition and Condition/Event factories)."""
        for cls in (n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)):
            locks, events = set(), set()
            for sub in ast.walk(cls):
                if not isinstance(sub, ast.Assign):
                    continue
                call = sub.value
                if not isinstance(call, ast.Call):
                    continue
                factory = _dotted(call.func) or ""
                leaf = factory.rsplit(".", 1)[-1]
                for target in sub.targets:
                    attr = _is_self_attr(target)
                    if attr is None:
                        continue
                    if leaf in _LOCK_FACTORIES or \
                            leaf in ("traced_lock", "traced_rlock"):
                        locks.add(attr)
                    if leaf in _EVENT_FACTORIES:
                        events.add(attr)
            cls._gl_locks = locks
            cls._gl_events = events

        def visit(node, scope, func, cls, lockdepth):
            node._gl_scope = scope
            node._gl_func = func
            node._gl_class = cls
            node._gl_lockdepth = lockdepth
            if isinstance(node, ast.ClassDef):
                scope = node.name if scope == "<module>" \
                    else f"{scope}.{node.name}"
                cls = node
                func = None
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = node.name if scope == "<module>" \
                    else f"{scope}.{node.name}"
                func = node.name
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                if any(self.is_lock_expr(item.context_expr, cls)
                       for item in node.items):
                    lockdepth += 1
            for child in ast.iter_child_nodes(node):
                visit(child, scope, func, cls, lockdepth)

        visit(self.tree, "<module>", None, None, 0)

    def is_lock_expr(self, expr: ast.AST, cls) -> bool:
        """Heuristic: does ``with <expr>:`` acquire a lock? True for
        self-attributes the class binds to a Lock factory, and for any
        name/attribute that *looks* like a lock (``_lock``, ``cv``,
        ``mutex``...)."""
        attr = _is_self_attr(expr)
        if attr is not None:
            if cls is not None and attr in getattr(cls, "_gl_locks", ()):
                return True
            return bool(_LOCKISH_NAME.search(attr))
        if isinstance(expr, ast.Name):
            return bool(_LOCKISH_NAME.search(expr.id))
        if isinstance(expr, ast.Attribute):
            return bool(_LOCKISH_NAME.search(expr.attr))
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       scope=getattr(node, "_gl_scope", "<module>"))


# ---------------------------------------------------------------------
# rules


_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "setdefault", "__setitem__",
}


@register
class UnguardedSharedState(Rule):
    id = "GL001"
    name = "unguarded-shared-state"
    rationale = ("a class that owns a lock mutates self._* state "
                 "outside any `with <lock>` block — racy once a second "
                 "thread touches the instance")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            cls = getattr(node, "_gl_class", None)
            if cls is None or not cls._gl_locks:
                continue
            if node._gl_func == "__init__" or node._gl_lockdepth > 0:
                continue
            attr = self._mutated_attr(node, cls)
            if attr is not None:
                names = sorted(cls._gl_locks)
                if len(names) > 3:
                    names = names[:3] + [f"+{len(names) - 3} more"]
                yield ctx.finding(
                    self.id, node,
                    f"mutation of self.{attr} outside the lock "
                    f"({'/'.join(names)}) this class owns")

    @staticmethod
    def _mutated_attr(node: ast.AST, cls) -> Optional[str]:
        def shared(target) -> Optional[str]:
            attr = _is_self_attr(target)
            if attr is not None and attr.startswith("_") \
                    and not attr.startswith("__") \
                    and attr not in cls._gl_locks:
                return attr
            return None

        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            return shared(node.func.value)
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            # read-modify-write on a self attr is racy even for scalars
            target = node.target
            if isinstance(target, ast.Subscript):
                return shared(target.value)
            return shared(target)
        else:
            return None
        for target in targets:
            if isinstance(target, ast.Subscript):
                attr = shared(target.value)
                if attr is not None:
                    return attr
        return None


_BLOCKING_EXACT = {"time.sleep", "ray_tpu.get", "subprocess.run",
                   "subprocess.call", "subprocess.check_call",
                   "subprocess.check_output", "subprocess.Popen",
                   "socket.create_connection"}
_BLOCKING_LEAF = {"sleep", "recv", "recv_into", "accept", "connect",
                  "gcs_call", "wait_for_nodes"}


@register
class LockHeldAcrossBlockingCall(Rule):
    id = "GL002"
    name = "lock-held-across-blocking-call"
    rationale = ("sleeping / socket IO / subprocess / RPC inside a "
                 "`with <lock>` body stalls every thread contending "
                 "for that lock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or node._gl_lockdepth == 0:
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            leaf = dotted.rsplit(".", 1)[-1]
            if dotted in _BLOCKING_EXACT or leaf in _BLOCKING_LEAF or \
                    dotted.startswith("subprocess."):
                yield ctx.finding(
                    self.id, node,
                    f"blocking call {dotted}() while holding a lock")


@register
class BusyWaitLoop(Rule):
    id = "GL003"
    name = "busy-wait-polling-loop"
    rationale = ("`while ...: time.sleep(...)` polling in a class that "
                 "already owns a Condition/Event — use a real wait "
                 "instead of burning wakeups and adding latency")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            cls = getattr(node, "_gl_class", None)
            if cls is None or not cls._gl_events:
                continue
            sleeps, waits = False, False
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if dotted.endswith("time.sleep") or dotted == "sleep":
                    sleeps = True
                if leaf in ("wait", "wait_for", "get", "join"):
                    waits = True
            if sleeps and not waits:
                yield ctx.finding(
                    self.id, node,
                    "busy-wait loop; this class owns "
                    f"{'/'.join(sorted(cls._gl_events))} — wait on it "
                    "instead of polling")


_LOGGISH = re.compile(r"(?:^|\.)(?:log|logger|logging|warn|warning|"
                      r"error|exception|debug|info|print_exc|print)")


@register
class SwallowedException(Rule):
    id = "GL004"
    name = "swallowed-exception"
    rationale = ("a bare `except:` or `except Exception: pass` hides "
                 "real failures; log it or justify the suppression")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if not self._handled(node):
                    yield ctx.finding(
                        self.id, node,
                        "bare `except:` traps SystemExit/"
                        "KeyboardInterrupt and hides failures")
                continue
            broad = isinstance(node.type, ast.Name) and \
                node.type.id in ("Exception", "BaseException")
            if broad and self._body_is_silent_pass(node) and \
                    not self._handled(node):
                yield ctx.finding(
                    self.id, node,
                    f"`except {node.type.id}: pass` swallows the "
                    "error without logging")

    @staticmethod
    def _body_is_silent_pass(node: ast.ExceptHandler) -> bool:
        return all(isinstance(stmt, ast.Pass) or
                   (isinstance(stmt, ast.Expr) and
                    isinstance(stmt.value, ast.Constant))
                   for stmt in node.body)

    @staticmethod
    def _handled(node: ast.ExceptHandler) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func)
                if dotted and _LOGGISH.search(dotted):
                    return True
        return False


_FORBIDDEN_IMPORTS = ("torch.cuda", "cupy", "nccl", "pynccl", "pycuda",
                      "pynvml", "cuda")


@register
class ForbiddenBackendImport(Rule):
    id = "GL005"
    name = "forbidden-backend-import"
    rationale = ("CUDA backends are compiled out of this TPU-native "
                 "build (BASELINE.md); torch.cuda/nccl/cupy must not "
                 "creep back in")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if self._forbidden(alias.name):
                        yield ctx.finding(
                            self.id, node,
                            f"import of CUDA backend {alias.name!r}")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if self._forbidden(mod):
                    yield ctx.finding(
                        self.id, node,
                        f"import from CUDA backend {mod!r}")
                elif mod == "torch":
                    for alias in node.names:
                        if alias.name == "cuda":
                            yield ctx.finding(
                                self.id, node,
                                "`from torch import cuda` — CUDA is "
                                "compiled out")
            elif isinstance(node, ast.Attribute):
                if _dotted(node) == "torch.cuda":
                    yield ctx.finding(self.id, node,
                                      "use of torch.cuda attribute")

    @staticmethod
    def _forbidden(module: str) -> bool:
        return any(module == root or module.startswith(root + ".")
                   for root in _FORBIDDEN_IMPORTS)


_METRIC_NAME_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")
# Unit/kind suffixes accepted per metric type. Counters are cumulative
# and must say so (_total); histograms measure a unit; gauges may also
# be dimensionless levels (_depth, _ratio, _requests...).
_METRIC_SUFFIXES = {
    "Counter": ("_total",),
    "Histogram": ("_seconds", "_bytes", "_size", "_tokens", "_ratio"),
    "Gauge": ("_seconds", "_bytes", "_ratio", "_depth", "_requests",
              "_tokens", "_total", "_size", "_count", "_percent",
              "_occupancy", "_workers", "_nodes", "_replicas", "_mfu",
              "_flag", "_info", "_actors", "_objects", "_tasks",
              "_per_second", "_steps", "_pending", "_fds"),
}


@register
class MetricNamingConvention(Rule):
    id = "GL006"
    name = "metric-naming-convention"
    rationale = ("every exported metric is `ray_tpu_`-prefixed "
                 "snake_case with a unit/kind suffix (`_total` for "
                 "counters) so dashboards and alerts survive refactors")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            kind = dotted.rsplit(".", 1)[-1]
            if kind not in _METRIC_SUFFIXES:
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if not _METRIC_NAME_RE.match(name):
                yield ctx.finding(
                    self.id, node,
                    f"metric {name!r} is outside the ray_tpu_ "
                    "snake_case convention")
            elif not name.endswith(_METRIC_SUFFIXES[kind]):
                yield ctx.finding(
                    self.id, node,
                    f"{kind} {name!r} lacks a unit/kind suffix "
                    f"(expected one of {_METRIC_SUFFIXES[kind]})")


@register
class TraceContextDrop(Rule):
    id = "GL007"
    name = "trace-context-drop"
    rationale = ("a TaskSpec built without trace_id breaks the "
                 "distributed trace at that hop (PR 1 wired trace "
                 "context end-to-end; new call sites must keep it)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            if dotted.rsplit(".", 1)[-1] != "TaskSpec":
                continue
            kw_names = {k.arg for k in node.keywords}
            if None in kw_names:  # **kwargs may carry it
                continue
            if "trace_id" not in kw_names:
                yield ctx.finding(
                    self.id, node,
                    "TaskSpec(...) without trace_id= — this hop drops "
                    "the request's trace context")


@register
class NonDaemonBackgroundThread(Rule):
    id = "GL008"
    name = "non-daemon-background-thread"
    rationale = ("a non-daemon background thread with no shutdown path "
                 "hangs interpreter exit (tests and drivers never "
                 "terminate)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # collect `<target>.daemon = True` assignments per scope
        daemonized: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                for target in node.targets:
                    if isinstance(target, ast.Attribute) and \
                            target.attr == "daemon":
                        base = _dotted(target.value) or ast.dump(
                            target.value)
                        daemonized.add((node._gl_scope, base))
        assigned_to: Dict[int, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for target in node.targets:
                    base = _dotted(target)
                    if base:
                        assigned_to[id(node.value)] = base
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            if dotted not in ("threading.Thread", "Thread"):
                continue
            kwargs = {k.arg: k.value for k in node.keywords}
            daemon = kwargs.get("daemon")
            if isinstance(daemon, ast.Constant) and daemon.value:
                continue
            if daemon is not None and not isinstance(daemon, ast.Constant):
                continue  # computed daemon-ness: give it the benefit
            target = assigned_to.get(id(node))
            if target and (node._gl_scope, target) in daemonized:
                continue
            yield ctx.finding(
                self.id, node,
                "threading.Thread(...) without daemon=True or a "
                "registered shutdown path")


# ---------------------------------------------------------------------
# engine


def _iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _rel(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:
        rel = path
    if rel.startswith(".." + os.sep):
        rel = path
    return rel.replace(os.sep, "/")


def lint_file(path: str, source: Optional[str] = None,
              select: Optional[Iterable[str]] = None,
              ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        ctx = FileContext(_rel(path), source)
    except SyntaxError as e:
        return [Finding(rule="GL000", path=_rel(path),
                        line=e.lineno or 1, col=e.offset or 0,
                        message=f"syntax error: {e.msg}",
                        scope="<module>")]
    selected = set(select) if select else set(RULES)
    if ignore:
        selected -= set(ignore)
    findings: List[Finding] = []
    for rule_id in sorted(selected):
        rule = RULES.get(rule_id)
        if rule is None:
            continue
        for finding in rule.check(ctx):
            if not ctx.suppressed(finding):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(paths: Sequence[str],
               select: Optional[Iterable[str]] = None,
               ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        findings.extend(lint_file(path, select=select, ignore=ignore))
    return findings


# -- baseline ----------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}")
    return dict(data.get("baseline", {}))


def write_baseline(findings: Iterable[Finding], path: str) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    payload = {
        "version": 1,
        "comment": ("grandfathered graftlint findings; regenerate with "
                    "`python -m ray_tpu.devtools.lint <paths> "
                    "--write-baseline`. New findings (even in a "
                    "baselined scope) still fail once the scope's "
                    "count is exceeded."),
        "baseline": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Drop up to baseline[key] findings per fingerprint (earliest
    lines win); everything beyond the grandfathered count is new."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            out.append(f)
    return out


def find_default_baseline(paths: Sequence[str]) -> Optional[str]:
    """cwd first, then ancestors of each scanned path."""
    candidates = [os.path.join(os.getcwd(), BASELINE_DEFAULT)]
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p))
        while True:
            candidates.append(os.path.join(d, BASELINE_DEFAULT))
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    for c in candidates:
        if os.path.isfile(c):
            return c
    return None


# -- CLI ---------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="framework-aware static analysis for ray_tpu")
    parser.add_argument("paths", nargs="*", default=["ray_tpu"])
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: "
                             f"{BASELINE_DEFAULT} in cwd or scanned-"
                             "path ancestors)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring baselines")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings as the baseline "
                             "and exit 0")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run")
    parser.add_argument("--ignore", default=None,
                        help="comma-separated rule ids to skip")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            rule = RULES[rid]
            print(f"{rid} {rule.name}: {rule.rationale}")
        return 0

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    findings = lint_paths(args.paths, select=select, ignore=ignore)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = find_default_baseline(args.paths)

    if args.write_baseline:
        out = baseline_path or BASELINE_DEFAULT
        write_baseline(findings, out)
        print(f"graftlint: wrote {len(findings)} finding(s) to {out}")
        return 0

    if baseline_path and not args.no_baseline:
        findings = apply_baseline(findings, load_baseline(baseline_path))

    for f in findings:
        print(f)
    if findings:
        by_rule: Dict[str, int] = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items()))
        print(f"graftlint: {len(findings)} finding(s) ({summary})")
        return 1
    print("graftlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
