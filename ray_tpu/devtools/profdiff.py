"""profdiff: regression diffing for profile / phase captures.

Compares two captures and reports per-phase and per-frame deltas with
PERF.md's ratio-based guard philosophy — absolute µs vary wildly across
machines, ratios between two captures taken on the SAME machine do not.
This is how the upcoming submit-path PRs land with "frame-encode
41 µs → 9 µs" evidence instead of a single end-to-end number.

Accepted capture formats (auto-detected, mix-and-match):

* phase tables — ``whereis.task_path_attribution()`` report dicts
  (``{"phases": {...}}``), ``perf.py --phases --json`` BENCH rows,
  or a whole BENCH_core.json list (the ``task_phases`` row is used);
* profiles — ``profiler.capture()`` dumps
  (``{"kind": "rtpu-profile", "procs": {...}}``);
* flight journals — ``ray_tpu.flight_journal()`` dumps (their
  ``task_phase`` events are folded on the fly);
* collsan fold dumps — ``collsan.capture()``
  (``{"kind": "rtpu-collsan", "groups": {...}}``): each
  ``group/op`` becomes a row whose magnitude is its total payload
  bytes and whose count is the number of rounds, so two runs'
  per-group collective traffic diffs like any phase table.

Usage::

    python -m ray_tpu.devtools.profdiff A.json B.json
    python -m ray_tpu.devtools.profdiff A.json B.json --fail-ratio 1.3

``--fail-ratio R`` exits non-zero when any phase's B/A mean-µs ratio
exceeds R (phases under ``--min-count`` samples are ignored — a
5-sample phase's mean is noise, not a regression).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Frames with fewer self-samples than this in BOTH captures are noise.
MIN_FRAME_SAMPLES = 5


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        payload = json.load(f)
    return normalize(payload)


def normalize(payload: Any) -> Dict[str, Any]:
    """Fold any accepted capture shape into
    ``{"phases": {name: mean_us}, "counts": {name: n},
       "frames": {frame: self_samples}, "samples": total}``."""
    phases: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    frames: Dict[str, int] = {}
    samples = 0

    if isinstance(payload, list):
        # BENCH_core.json: use the task_phases row
        row = next((r for r in payload
                    if isinstance(r, dict)
                    and r.get("bench") == "task_phases"), None)
        payload = row or {}

    if isinstance(payload, dict) and payload.get("kind") == "rtpu-collsan":
        # collsan capture: group/op rows, magnitude = payload bytes
        for group, ops in sorted((payload.get("groups") or {}).items()):
            for op, row in sorted(ops.items()):
                name = f"{group}/{op}"
                phases[name] = float(row.get("bytes", 0))
                counts[name] = int(row.get("count", 0))
        return {"phases": phases, "counts": counts, "frames": frames,
                "samples": samples}

    if isinstance(payload, dict) and "journals" in payload:
        from ray_tpu.devtools import whereis
        payload = whereis.task_path_attribution(
            {label: [tuple(ev) for ev in events]
             for label, events in payload["journals"].items()})

    if isinstance(payload, dict):
        for name, row in (payload.get("phases") or {}).items():
            if isinstance(row, dict):
                if row.get("mean_us") is not None:
                    phases[name] = float(row["mean_us"])
                counts[name] = int(row.get("count", 0))
            else:  # bare {phase: mean_us} tables are fine too
                phases[name] = float(row)
        for snap in (payload.get("procs") or {}).values():
            for stack, n in (snap.get("counts") or {}).items():
                leaf = stack.rsplit(";", 1)[-1]
                frames[leaf] = frames.get(leaf, 0) + int(n)
                samples += int(n)
    return {"phases": phases, "counts": counts, "frames": frames,
            "samples": samples}


def diff(a: Dict[str, Any], b: Dict[str, Any],
         min_count: int = 0) -> Dict[str, Any]:
    """Per-phase mean-µs deltas (+ ratios) and per-frame self-sample
    share deltas between two normalized captures."""
    phase_rows: List[Dict[str, Any]] = []
    for name in sorted(set(a["phases"]) | set(b["phases"])):
        va, vb = a["phases"].get(name), b["phases"].get(name)
        row: Dict[str, Any] = {"phase": name, "a_us": va, "b_us": vb,
                               "count_a": a["counts"].get(name, 0),
                               "count_b": b["counts"].get(name, 0)}
        if va is not None and vb is not None:
            row["delta_us"] = round(vb - va, 2)
            row["ratio"] = round(vb / va, 3) if va > 0 else None
        phase_rows.append(row)

    frame_rows: List[Dict[str, Any]] = []
    sa, sb = a["samples"], b["samples"]
    if sa and sb:
        for frame in set(a["frames"]) | set(b["frames"]):
            na, nb = a["frames"].get(frame, 0), b["frames"].get(frame, 0)
            if max(na, nb) < MIN_FRAME_SAMPLES:
                continue
            fa, fb = na / sa, nb / sb
            frame_rows.append({
                "frame": frame, "a_pct": round(fa * 100, 2),
                "b_pct": round(fb * 100, 2),
                "delta_pct": round((fb - fa) * 100, 2),
            })
        frame_rows.sort(key=lambda r: -abs(r["delta_pct"]))

    worst = None
    for row in phase_rows:
        if row.get("ratio") is None:
            continue
        if min_count and min(row["count_a"], row["count_b"]) < min_count:
            continue
        if worst is None or row["ratio"] > worst["ratio"]:
            worst = row
    return {"phases": phase_rows, "frames": frame_rows, "worst": worst}


def render(report: Dict[str, Any], fail_ratio: Optional[float] = None
           ) -> str:
    lines = ["profdiff: B vs A (ratio > 1 means B is slower)"]
    if report["phases"]:
        lines.append("  %-16s %10s %10s %10s %8s"
                     % ("phase", "A_us", "B_us", "delta_us", "ratio"))
        for row in report["phases"]:
            fmt = lambda v: "—" if v is None else f"{v:.2f}"  # noqa: E731
            ratio = row.get("ratio")
            flag = ""
            if fail_ratio is not None and ratio is not None:
                if ratio > fail_ratio:
                    flag = "  << REGRESSION"
                elif ratio < 1.0 / fail_ratio:
                    flag = "  << improved"
            lines.append("  %-16s %10s %10s %10s %8s%s"
                         % (row["phase"], fmt(row["a_us"]),
                            fmt(row["b_us"]),
                            fmt(row.get("delta_us")),
                            "—" if ratio is None else f"{ratio:.3f}",
                            flag))
    if report["frames"]:
        lines.append("  top frame movers (self-sample share):")
        for row in report["frames"][:15]:
            lines.append("    %-48s %6.2f%% -> %6.2f%%  (%+.2f%%)"
                         % (row["frame"][:48], row["a_pct"],
                            row["b_pct"], row["delta_pct"]))
    if not report["phases"] and not report["frames"]:
        lines.append("  (captures share no comparable phases or frames)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args: List[str] = []
    fail_ratio: Optional[float] = None
    min_count = 0
    it = iter(argv)
    for tok in it:
        if tok == "--fail-ratio":
            fail_ratio = float(next(it))
        elif tok == "--min-count":
            min_count = int(next(it))
        elif tok.startswith("--"):
            args = []           # unknown flag: force the usage message
            break
        else:
            args.append(tok)
    if len(args) != 2:
        print("usage: python -m ray_tpu.devtools.profdiff A.json B.json"
              " [--fail-ratio R] [--min-count N]", file=sys.stderr)
        return 2
    a, b = _load(args[0]), _load(args[1])
    report = diff(a, b, min_count=min_count)
    print(render(report, fail_ratio=fail_ratio))
    worst = report["worst"]
    if (fail_ratio is not None and worst is not None
            and worst["ratio"] is not None
            and worst["ratio"] > fail_ratio):
        print(f"FAIL: {worst['phase']} ratio {worst['ratio']:.3f} > "
              f"{fail_ratio}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
