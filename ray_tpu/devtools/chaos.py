"""Deterministic fault injection for cluster envelope drills.

Capability parity with the reference's chaos tooling (reference:
src/ray/common/test/testing chaos hooks + the nightly chaos-test suite
killing raylets on a schedule). A :class:`ChaosSchedule` is an explicit,
seed-reproducible timeline of :class:`ChaosFault` entries; a
:class:`ChaosController` executes it against live cluster members —
virtual nodes (``core/virtual_node.py``), daemon subprocesses (via
:class:`DaemonHandle`), or the head's in-process workers — from ONE
timer thread.

Every injected fault is recorded as a ``CHAOS_INJECTED`` cluster event
*before* the fault lands, and its seq is stashed on the head-side node
object (``_chaos_cause_seq`` for node faults, ``_chaos_worker_causes``
for worker kills), so the death events the fault triggers chain to it
via ``caused_by`` and ``devtools/recovery.py`` attributes each incident
to its injected root cause::

    CHAOS_INJECTED -> NODE_DEAD                      (kill drill)
    CHAOS_INJECTED -> NODE_HEARTBEAT_MISS -> NODE_DEAD  (freeze drill)
    CHAOS_INJECTED -> WORKER_EXIT                    (worker kill)

Fault vocabulary (``ChaosFault.kind``):

==============  ========================================================
kind            effect on the target node
==============  ========================================================
kill_node       sever/SIGKILL — abrupt EOF death at the head
freeze_node     SIGSTOP analog — heartbeats stop, traffic held; the
                head declares death after ``heartbeat_timeout_s``
thaw_node       resume a frozen node (SIGCONT analog)
kill_worker     kill one worker/actor process on the node
shrink_store    multiply the node's object-store capacity by
                ``factor`` (spill-pressure drill; virtual nodes only)
delay_wire      install a codec shim delaying inbound frames by
                ``delay_s`` on NEW connections (``io_loop._codec_wrapper``)
drop_wire       codec shim dropping inbound frames with probability
                ``drop_p`` (seeded) on NEW connections
clear_wire      remove any installed codec shim
==============  ========================================================

Schedules serialize to/from plain dicts (JSON-ready) so drills can pin
them in fixtures; ``ChaosSchedule.from_seed`` derives a reproducible
kill/freeze mix from one integer.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

FAULT_KINDS = ("kill_node", "freeze_node", "thaw_node", "kill_worker",
               "shrink_store", "delay_wire", "drop_wire", "clear_wire")


@dataclass
class ChaosFault:
    """One timed fault. ``target`` indexes the controller's target
    list (int) — stable across runs for a fixed schedule — or names a
    node id hex prefix (str). Wire faults need no target."""

    at_s: float
    kind: str
    target: Optional[Any] = None
    args: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"at_s": self.at_s, "kind": self.kind,
                "target": self.target, "args": dict(self.args)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosFault":
        return cls(at_s=float(d["at_s"]), kind=d["kind"],
                   target=d.get("target"), args=dict(d.get("args") or {}))


@dataclass
class ChaosSchedule:
    """An ordered fault timeline (relative to controller start)."""

    faults: List[ChaosFault] = field(default_factory=list)
    seed: Optional[int] = None

    def __post_init__(self):
        self.faults.sort(key=lambda f: f.at_s)
        for fault in self.faults:
            if fault.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind: {fault.kind!r}")

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "faults": [f.to_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosSchedule":
        return cls(faults=[ChaosFault.from_dict(f)
                           for f in d.get("faults", ())],
                   seed=d.get("seed"))

    @classmethod
    def from_seed(cls, seed: int, *, n_targets: int, duration_s: float,
                  kills: int = 1, freezes: int = 0,
                  worker_kills: int = 0,
                  start_s: float = 0.1) -> "ChaosSchedule":
        """Derive a reproducible schedule: ``kills``/``freezes``/
        ``worker_kills`` faults spread uniformly over ``duration_s``
        against distinct targets drawn without replacement (so a node
        is not killed twice)."""
        rng = random.Random(seed)
        total = kills + freezes + worker_kills
        if total > n_targets:
            raise ValueError(
                f"{total} faults need {total} distinct targets, "
                f"have {n_targets}")
        targets = rng.sample(range(n_targets), total)
        times = sorted(rng.uniform(start_s, duration_s)
                       for _ in range(total))
        kinds = (["kill_node"] * kills + ["freeze_node"] * freezes
                 + ["kill_worker"] * worker_kills)
        rng.shuffle(kinds)
        return cls(faults=[ChaosFault(at_s=t, kind=k, target=i)
                           for t, k, i in zip(times, kinds, targets)],
                   seed=seed)


class DaemonHandle:
    """Adapter presenting a real node-daemon subprocess as a chaos
    target: kill/freeze/thaw map to SIGKILL/SIGSTOP/SIGCONT."""

    def __init__(self, node_id, proc):
        self.node_id = node_id
        self.proc = proc

    def kill(self) -> None:
        try:
            self.proc.kill()
        except ProcessLookupError:
            pass

    def freeze(self) -> None:
        import signal
        try:
            self.proc.send_signal(signal.SIGSTOP)
        except ProcessLookupError:
            pass

    def thaw(self) -> None:
        import signal
        try:
            self.proc.send_signal(signal.SIGCONT)
        except ProcessLookupError:
            pass


class ChaosCodec:
    """Codec shim injecting wire faults on the inbound path. Wraps the
    real codec chosen by ``io_loop._make_codec``; outbound passes
    through untouched. ``delay_s`` holds decoded frames until their
    release time (delivered on a later read — delivery granularity is
    the socket's read cadence, fine for drills); ``drop_p`` drops
    frames with seeded probability."""

    def __init__(self, inner, delay_s: float = 0.0, drop_p: float = 0.0,
                 rng: Optional[random.Random] = None):
        self._inner = inner
        self.native = getattr(inner, "native", False)
        self._delay_s = delay_s
        self._drop_p = drop_p
        self._rng = rng or random.Random(0)
        self._held: List[Tuple[float, bytes]] = []

    def read(self, sock):
        frames, status = self._inner.read(sock)
        if self._drop_p > 0.0:
            frames = [f for f in frames
                      if self._rng.random() >= self._drop_p]
        if self._delay_s > 0.0:
            now = time.monotonic()
            self._held.extend((now + self._delay_s, f) for f in frames)
            ready = []
            while self._held and self._held[0][0] <= now:
                ready.append(self._held.pop(0)[1])
            frames = ready
        return frames, status

    # outbound/writer surface: pure pass-through
    def enqueue(self, payload):
        return self._inner.enqueue(payload)

    def flush(self, sock):
        return self._inner.flush(sock)

    def queued(self):
        return self._inner.queued()

    def feed(self, data):
        return self._inner.feed(data)

    def leftover(self):
        return self._inner.leftover()


class ChaosController:
    """Executes a :class:`ChaosSchedule` against live targets.

    ``targets`` is an ordered list of handles — any object with
    ``node_id`` plus ``kill()``/``freeze()``/``thaw()``
    (:class:`~ray_tpu.core.virtual_node.VirtualNode`,
    :class:`DaemonHandle`) — or head-side NodeIDs for in-process nodes
    (kill_node then maps to ``runtime.remove_node``). One daemon thread
    walks the timeline; ``injected`` collects ``(fault, seq,
    node_id_hex)`` for drill assertions.
    """

    def __init__(self, runtime, schedule: ChaosSchedule,
                 targets: List[Any]):
        self.runtime = runtime
        self.schedule = schedule
        self.targets = list(targets)
        self.injected: List[Tuple[ChaosFault, Optional[int],
                                  Optional[str]]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle -------------------------------------------------------
    def start(self) -> "ChaosController":
        self._thread = threading.Thread(target=self._run,
                                        name="chaos-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def run_sync(self) -> None:
        """Execute the whole schedule on the calling thread."""
        self._run()

    def __enter__(self) -> "ChaosController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
        self.join(timeout=5.0)
        clear_wire_faults()

    # --- execution -------------------------------------------------------
    def _run(self) -> None:
        t0 = time.monotonic()
        for fault in self.schedule.faults:
            delay = fault.at_s - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            try:
                self._inject(fault)
            except Exception:  # noqa: BLE001 — a failed injection must
                # not kill the drill thread mid-schedule
                import traceback
                traceback.print_exc()

    def _resolve(self, fault: ChaosFault):
        """(handle, head_node_obj, node_id) for the fault's target."""
        target = fault.target
        handle = None
        if isinstance(target, int):
            if not self.targets:
                return None, None, None
            handle = self.targets[target % len(self.targets)]
        elif isinstance(target, str):
            for cand in self.targets:
                if cand.node_id.hex().startswith(target):
                    handle = cand
                    break
            if handle is None:
                return None, None, None
        elif target is not None:
            handle = target
        if handle is None:
            return None, None, None
        node_id = getattr(handle, "node_id", handle)
        return handle, self.runtime.nodes.get(node_id), node_id

    def _emit(self, fault: ChaosFault, node_id,
              extra: Optional[dict] = None) -> Optional[int]:
        data = {"fault": fault.kind, "at_s": round(fault.at_s, 3)}
        if self.schedule.seed is not None:
            data["seed"] = self.schedule.seed
        if extra:
            data.update(extra)
        seq = self.runtime.gcs.add_cluster_event(
            "CHAOS_INJECTED", "WARNING", node_id=node_id,
            message=f"injected {fault.kind}", data=data)
        self.injected.append(
            (fault, seq, node_id.hex() if node_id is not None else None))
        return seq

    def _inject(self, fault: ChaosFault) -> None:
        kind = fault.kind
        if kind in ("delay_wire", "drop_wire", "clear_wire"):
            self._emit(fault, None, dict(fault.args))
            if kind == "clear_wire":
                clear_wire_faults()
            else:
                install_wire_faults(
                    delay_s=float(fault.args.get("delay_s", 0.0)),
                    drop_p=float(fault.args.get("drop_p", 0.0)),
                    seed=self.schedule.seed or 0)
            return
        handle, head_node, node_id = self._resolve(fault)
        if handle is None:
            return
        # Record BEFORE injecting: the death observers read the stashed
        # seq when the fault lands, never before.
        seq = self._emit(fault, node_id)
        if kind in ("kill_node", "freeze_node"):
            if head_node is not None:
                head_node._chaos_cause_seq = seq
        if kind == "kill_node":
            if hasattr(handle, "kill"):
                handle.kill()
            else:
                self.runtime.remove_node(node_id)
        elif kind == "freeze_node":
            handle.freeze()
        elif kind == "thaw_node":
            handle.thaw()
        elif kind == "kill_worker":
            self._kill_worker(handle, head_node, node_id, seq)
        elif kind == "shrink_store":
            store = getattr(handle, "store", None)
            if store is not None and hasattr(store, "_capacity"):
                factor = float(fault.args.get("factor", 0.5))
                store._capacity = max(1, int(store._capacity * factor))

    def _kill_worker(self, handle, head_node, node_id, seq) -> None:
        """Kill one worker on the target node, stashing the cause seq
        where the matching WORKER_EXIT emit site will find it."""
        # virtual node: actor cells are its only long-lived workers
        actors = getattr(handle, "_actors", None)
        if actors is not None:
            with handle._lock:
                wids = list(actors)
            if not wids or head_node is None:
                return
            wid = wids[0]
            causes = getattr(head_node, "_chaos_worker_causes", None)
            if causes is None:
                causes = head_node._chaos_worker_causes = {}
            causes[wid] = seq
            head_node.kill_worker(wid)
            return
        # in-process node: pick a live worker handle, tag it, kill it
        node = self.runtime.nodes.get(node_id)
        workers = getattr(node, "_workers", None)
        if not workers:
            return
        with node._lock:
            items = list(workers.items())
        for wid, worker in items:
            worker._chaos_cause_seq = seq
            node.kill_worker(wid)
            return


# --- wire-fault installation (io_loop._codec_wrapper seam) --------------

def install_wire_faults(delay_s: float = 0.0, drop_p: float = 0.0,
                        seed: int = 0) -> None:
    """Install a :class:`ChaosCodec` shim for NEW connections. Existing
    connections keep their codec — point drills at reconnect paths or
    install before dialing."""
    from ray_tpu.core import io_loop
    rng = random.Random(seed)

    def wrapper(inner):
        return ChaosCodec(inner, delay_s=delay_s, drop_p=drop_p, rng=rng)

    io_loop._codec_wrapper = wrapper


def clear_wire_faults() -> None:
    from ray_tpu.core import io_loop
    io_loop._codec_wrapper = None
