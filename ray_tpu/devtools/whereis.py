"""whereis: step-time attribution from the flight-recorder journal.

Answers "where did my step time go" by folding the merged (clock-
aligned) journals into per-step fractions:

* **compute** — FWD/BWD/STEP instruction time on pipeline stages,
* **comms**   — SEND/RECV channel time plus collective-hop time,
* **data_wait** — prefetch consumer stalls (the trainer starving on
  input) measured on the consuming process,
* **bubble**  — ``1 - compute/wall`` per stage, the SAME formula the
  live pipeline report uses (so the measured number here must agree
  with ``PipelineRunner.step()``'s within noise),
* **idle**    — whatever the named categories don't cover.

Podracer RL runs (category ``rl``) get their own rollup: time is
attributed into **acting** (env-runner rollouts), **inference-wait**
(batched policy forwards the actors block on), **learning** (learner
updates) and **weight-sync** (quantized weight broadcasts), plus the
learner's replay-queue wait — the Sebulba version of "where did my
step time go".

Usage::

    ray_tpu.whereis()                      # live, after some steps ran
    ray_tpu.flight_journal("run.json")     # dump for offline analysis
    python -m ray_tpu.devtools.whereis run.json

The theoretical bubble is recomputed from the schedule parameters the
stage spans carry (``(S-1)/(M+S-1)`` for 1F1B/GPipe) and printed next
to the measured one — the gap is what schedule tuning can recover.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

_COMPUTE_OPS = ("FWD", "BWD", "STEP")
_COMMS_OPS = ("SEND", "RECV")


def attribution(journals: Optional[Dict[str, List[tuple]]] = None
                ) -> Dict[str, Any]:
    """Fold journals (label -> aligned event tuples) into the
    attribution report. With no argument, reads the live merged
    journals from the flight recorder."""
    if journals is None:
        from ray_tpu.util import flight_recorder
        journals = flight_recorder.merged_journals()

    # per (stage, step): wall/compute from the stage_step envelope,
    # comms summed from SEND/RECV instruction spans
    per: Dict[tuple, Dict[str, float]] = {}
    sched_params = None  # (schedule, S, M) off any stage_step span
    data_wait_ns = 0
    coll_count = 0
    coll_wire = 0
    coll_ratios: List[float] = []
    # Podracer RL spans → acting / inference-wait / learning /
    # weight-sync (plus the learner's replay wait)
    rl_ns = {"acting": 0, "inference_wait": 0, "learning": 0,
             "weight_sync": 0, "replay_wait": 0}
    rl_env_steps = 0
    rl_seen = False
    t_lo: Optional[int] = None
    t_hi: Optional[int] = None

    for label, events in journals.items():
        for seq, t0, dur, cat, name, args in events:
            t_lo = t0 if t_lo is None else min(t_lo, t0)
            t_hi = (t0 + dur) if t_hi is None else max(t_hi, t0 + dur)
            if cat == "pipeline":
                a = args or {}
                key = (a.get("stage"), a.get("step"))
                entry = per.setdefault(
                    key, {"wall_s": 0.0, "compute_s": 0.0,
                          "comms_s": 0.0})
                if name == "stage_step":
                    entry["wall_s"] = float(a.get("wall_s",
                                                  dur / 1e9))
                    entry["compute_s"] = float(a.get("compute_s", 0.0))
                    if a.get("schedule") is not None:
                        sched_params = (a.get("schedule"), a.get("S"),
                                        a.get("m"))
                elif name in _COMMS_OPS:
                    entry["comms_s"] += dur / 1e9
            elif cat == "prefetch" and name == "consumer_wait":
                data_wait_ns += dur
            elif cat == "collective":
                coll_count += 1
                a = args or {}
                coll_wire += int(a.get("wire", 0))
                if "ratio" in (a or {}):
                    coll_ratios.append(float(a["ratio"]))
            elif cat == "rl":
                rl_seen = True
                a = args or {}
                if name == "rollout":
                    rl_ns["acting"] += dur
                    rl_env_steps += int(a.get("env_steps", 0))
                elif name == "infer_batch":
                    rl_ns["inference_wait"] += dur
                elif name == "learn_step":
                    rl_ns["learning"] += dur
                    # Anakin has no rollout spans: the fused step IS
                    # the rollout, so its env steps ride learn_step
                    if a.get("arch") == "anakin":
                        rl_env_steps += int(a.get("env_steps", 0))
                elif name == "weight_push":
                    rl_ns["weight_sync"] += dur
                elif name == "replay_wait":
                    rl_ns["replay_wait"] += dur

    steps = {k: v for k, v in per.items() if v["wall_s"] > 0}
    wall = sum(v["wall_s"] for v in steps.values())
    compute = sum(v["compute_s"] for v in steps.values())
    comms = sum(v["comms_s"] for v in steps.values())
    window_s = ((t_hi - t_lo) / 1e9 if t_hi is not None else 0.0)
    data_wait_s = data_wait_ns / 1e9

    # per-stage rollup (bubble = 1 - compute/wall, the live formula)
    per_stage: Dict[Any, Dict[str, float]] = {}
    for (stage, _step), v in steps.items():
        agg = per_stage.setdefault(
            stage, {"steps": 0, "wall_s": 0.0, "compute_s": 0.0,
                    "comms_s": 0.0})
        agg["steps"] += 1
        agg["wall_s"] += v["wall_s"]
        agg["compute_s"] += v["compute_s"]
        agg["comms_s"] += v["comms_s"]
    for agg in per_stage.values():
        agg["bubble"] = (max(0.0, 1.0 - agg["compute_s"]
                             / agg["wall_s"])
                         if agg["wall_s"] > 0 else 0.0)

    measured_bubble = (sum(a["bubble"] for a in per_stage.values())
                       / len(per_stage)) if per_stage else None

    theoretical = None
    if sched_params and sched_params[1] and sched_params[2]:
        try:
            from ray_tpu.train.pipeline import schedule as sched_mod
            theoretical = sched_mod.bubble_fraction(
                int(sched_params[1]), int(sched_params[2]),
                sched_params[0])
        except Exception:  # noqa: BLE001 — old dump, unknown schedule
            theoretical = None

    frac = {}
    if wall > 0:
        c = compute / wall
        m = comms / wall
        d = min(1.0, data_wait_s / window_s) if window_s > 0 else 0.0
        frac = {"compute": round(c, 4), "comms": round(m, 4),
                "data_wait": round(d, 4),
                "bubble": round(max(0.0, 1.0 - c), 4),
                "idle": round(max(0.0, 1.0 - c - m), 4)}

    rl_report = None
    if rl_seen:
        total_ns = sum(rl_ns[k] for k in
                       ("acting", "inference_wait", "learning",
                        "weight_sync"))
        rl_report = {k + "_s": round(v / 1e9, 6)
                     for k, v in rl_ns.items()}
        rl_report["env_steps"] = rl_env_steps
        if window_s > 0 and rl_env_steps:
            rl_report["env_steps_per_sec"] = round(
                rl_env_steps / window_s, 1)
        if total_ns > 0:
            rl_report["fractions"] = {
                k: round(rl_ns[k] / total_ns, 4)
                for k in ("acting", "inference_wait", "learning",
                          "weight_sync")}

    return {
        "steps": len({k[1] for k in steps}),
        "stages": len(per_stage),
        "window_s": round(window_s, 6),
        "fractions": frac,
        "per_stage": {str(k): {kk: (round(vv, 6)
                                    if isinstance(vv, float) else vv)
                               for kk, vv in v.items()}
                      for k, v in sorted(per_stage.items(),
                                         key=lambda kv: str(kv[0]))},
        "measured_bubble": (round(measured_bubble, 4)
                            if measured_bubble is not None else None),
        "theoretical_bubble": (round(theoretical, 4)
                               if theoretical is not None else None),
        "data_wait_s": round(data_wait_s, 6),
        "collectives": {"count": coll_count, "wire_bytes": coll_wire,
                        "mean_compression_ratio": (
                            round(sum(coll_ratios) / len(coll_ratios),
                                  3) if coll_ratios else None)},
        "rl": rl_report,
    }


# --- submit-path phase attribution (PR 18) ---------------------------
# core/task_phase.py brackets 1-in-N submissions into a contiguous
# spec-build → result-return chain of ``task_phase`` events; this fold
# turns them into the per-phase µs budget ROADMAP item 2 is judged
# against. ``coverage`` is the union of the sampled chains' spans over
# the window — the fraction of submit+drain wall time the table
# accounts for (acceptance bar: ≥ 0.85 on the 20k-task harness).

def task_path_attribution(
        journals: Optional[Dict[str, List[tuple]]] = None,
        window_ns: Optional[tuple] = None) -> Dict[str, Any]:
    """Fold ``task_phase`` events into {phase: {count, total_us,
    mean_us, p50_us, p99_us}} plus chain-level coverage. ``window_ns``
    is an optional (lo, hi) pair in the driver clock domain (the bench
    harness passes its measured submit+drain window); without it the
    span of the phase events themselves is used."""
    if journals is None:
        from ray_tpu.util import flight_recorder
        journals = flight_recorder.merged_journals()

    from ray_tpu.core.task_phase import PHASES
    per: Dict[str, List[int]] = {}
    intervals: List[tuple] = []
    for label, events in journals.items():
        for seq, t0, dur, cat, name, args in events:
            if cat != "task_phase":
                continue
            per.setdefault(name, []).append(dur)
            intervals.append((t0, t0 + dur))

    if window_ns is not None:
        lo, hi = window_ns
    elif intervals:
        lo = min(iv[0] for iv in intervals)
        hi = max(iv[1] for iv in intervals)
    else:
        lo = hi = 0

    # union of chain spans, clipped to the window
    covered = 0
    cur_lo = cur_hi = None
    for s, e in sorted(intervals):
        s, e = max(s, lo), min(e, hi)
        if e <= s:
            continue
        if cur_hi is None or s > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = s, e
        else:
            cur_hi = max(cur_hi, e)
    if cur_hi is not None:
        covered += cur_hi - cur_lo
    window = hi - lo
    coverage = covered / window if window > 0 else None

    def _q(durs: List[int], q: float) -> Optional[float]:
        if not durs:
            return None
        i = min(len(durs) - 1, int(q * len(durs)))
        return durs[i] / 1e3

    phases: Dict[str, Dict[str, Any]] = {}
    order = [p for p in PHASES if p in per] + sorted(
        p for p in per if p not in PHASES)
    for name in order:
        durs = sorted(per[name])
        total = sum(durs)
        phases[name] = {
            "count": len(durs),
            "total_us": round(total / 1e3, 1),
            "mean_us": round(total / len(durs) / 1e3, 2),
            "p50_us": round(_q(durs, 0.50), 2),
            "p99_us": round(_q(durs, 0.99), 2),
        }

    tasks = len(per.get("result-return", ()))
    chain_total = sum(v["total_us"] for v in phases.values())
    # e2e percentiles off the live histogram when available — tolerant
    # of empty/None snapshots (util/metrics.py returns None, never
    # raises, on an unobserved series)
    e2e = {}
    try:
        from ray_tpu.core.task_manager import TASK_E2E_SECONDS
        for q in (0.5, 0.99):
            value = TASK_E2E_SECONDS.percentile(q)
            if value is not None:
                e2e[f"p{int(q * 100)}_ms"] = round(value * 1e3, 3)
    except Exception:  # graftlint: disable=GL004
        pass  # offline dumps have no runtime/registry to read from

    return {
        "phases": phases,
        "tasks_sampled": tasks,
        "mean_chain_us": (round(chain_total / tasks, 1)
                          if tasks else None),
        "window_s": round(window / 1e9, 6),
        "coverage": (round(coverage, 4)
                     if coverage is not None else None),
        "task_e2e": e2e or None,
    }


def render_task_path(report: Dict[str, Any]) -> str:
    lines = ["submit-path phase budget (flight recorder, sampled)"]
    lines.append(
        f"  tasks sampled: {report['tasks_sampled']}  "
        f"window: {report['window_s'] * 1e3:.1f}ms  "
        + (f"coverage: {report['coverage'] * 100:.1f}%"
           if report["coverage"] is not None else "coverage: n/a"))
    lines.append("  %-16s %8s %10s %10s %10s %12s"
                 % ("phase", "count", "mean_us", "p50_us", "p99_us",
                    "total_ms"))
    for name, row in report["phases"].items():
        lines.append("  %-16s %8d %10.2f %10.2f %10.2f %12.2f"
                     % (name, row["count"], row["mean_us"],
                        row["p50_us"], row["p99_us"],
                        row["total_us"] / 1e3))
    if report["mean_chain_us"] is not None:
        lines.append(f"  mean sampled chain: "
                     f"{report['mean_chain_us']:.1f}us/task")
    e2e = report.get("task_e2e")
    if e2e:
        lines.append("  task e2e: " + "  ".join(
            f"{k}={v}" for k, v in e2e.items()))
    return "\n".join(lines)


def render(report: Dict[str, Any]) -> str:
    lines = ["step-time attribution (flight recorder)"]
    lines.append(f"  pipeline stages: {report['stages']}  "
                 f"steps: {report['steps']}  "
                 f"window: {report['window_s'] * 1e3:.1f}ms")
    frac = report.get("fractions") or {}
    if frac:
        lines.append(
            "  compute %5.1f%%  comms %5.1f%%  data-wait %5.1f%%  "
            "bubble %5.1f%%  idle %5.1f%%" % (
                frac["compute"] * 100, frac["comms"] * 100,
                frac["data_wait"] * 100, frac["bubble"] * 100,
                frac["idle"] * 100))
    mb, tb = report["measured_bubble"], report["theoretical_bubble"]
    if mb is not None:
        line = f"  measured bubble: {mb:.3f}"
        if tb is not None:
            line += f"  theoretical: {tb:.3f}  gap: {mb - tb:+.3f}"
        lines.append(line)
    for stage, agg in report["per_stage"].items():
        lines.append(
            f"  stage {stage}: steps={agg['steps']} "
            f"wall={agg['wall_s'] * 1e3:.1f}ms "
            f"compute={agg['compute_s'] * 1e3:.1f}ms "
            f"comms={agg['comms_s'] * 1e3:.1f}ms "
            f"bubble={agg['bubble']:.3f}")
    coll = report["collectives"]
    if coll["count"]:
        lines.append(
            f"  collectives: {coll['count']} hops, "
            f"{coll['wire_bytes']} wire bytes, "
            f"ratio={coll['mean_compression_ratio']}")
    if report["data_wait_s"]:
        lines.append(
            f"  data wait: {report['data_wait_s'] * 1e3:.1f}ms")
    rl = report.get("rl")
    if rl:
        rf = rl.get("fractions") or {}
        if rf:
            lines.append(
                "  rl: acting %5.1f%%  inference-wait %5.1f%%  "
                "learning %5.1f%%  weight-sync %5.1f%%" % (
                    rf["acting"] * 100, rf["inference_wait"] * 100,
                    rf["learning"] * 100, rf["weight_sync"] * 100))
        line = (f"  rl: env steps {rl['env_steps']}  "
                f"replay wait {rl['replay_wait_s'] * 1e3:.1f}ms")
        if "env_steps_per_sec" in rl:
            line += f"  ({rl['env_steps_per_sec']:.0f} steps/s)"
        lines.append(line)
    return "\n".join(lines)


def _load_journals(path: str) -> Dict[str, List[tuple]]:
    with open(path) as f:
        payload = json.load(f)
    journals = payload.get("journals", payload)
    return {label: [tuple(ev) for ev in events]
            for label, events in journals.items()}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    task_path = "--task-path" in argv
    argv = [a for a in argv if a != "--task-path"]
    if not argv:
        print("usage: python -m ray_tpu.devtools.whereis "
              "[--task-path] <journal.json>\n(write one with "
              "ray_tpu.flight_journal('journal.json'))",
              file=sys.stderr)
        return 2
    journals = _load_journals(argv[0])
    if task_path:
        print(render_task_path(task_path_attribution(journals)))
    else:
        print(render(attribution(journals)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
