"""locktrace: runtime lock-order / deadlock-risk detector.

graftlint (static) can't see dynamic acquisition ORDER — the classic
distributed-control-plane deadlock is thread 1 taking A then B while
thread 2 takes B then A, each hop hidden behind a method call. This
module wraps ``threading.Lock``/``RLock`` with an instrumented proxy
that records, per thread, the stack of currently-held locks; every
nested acquisition adds an edge to a global lock-order graph. A cycle
in that graph is a potential deadlock even if the run never actually
deadlocked. It also flags holds that exceed a threshold (a lock held
across a blocking call — GL002's runtime twin).

Zero-cost when off: production call sites use the factories

    from ray_tpu.devtools import locktrace
    self._lock = locktrace.traced_lock("serve.router")

which return a *plain* ``threading.Lock`` unless ``RAY_TPU_LOCKTRACE=1``
is set (tests set it, or construct ``TracedLock`` directly).

Report shape (``locktrace.report()``)::

    {"cycles":     [["serve.router", "serve.replica"], ...],
     "long_holds": [{"lock", "held_s", "stack"}, ...],
     "edges":      [["a", "b"], ...]}
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

_ENV_FLAG = "RAY_TPU_LOCKTRACE"


def enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").lower() in ("1", "true", "yes")


class LockTracer:
    """Global acquisition-order recorder. Thread-safe; its own internal
    lock is a plain ``threading.Lock`` (never a TracedLock — the tracer
    must not trace itself)."""

    def __init__(self, hold_threshold_s: float = 0.5,
                 stack_depth: int = 12):
        self.hold_threshold_s = hold_threshold_s
        self.stack_depth = stack_depth
        self._mu = threading.Lock()
        # (held_name, acquired_name) -> sample stack at the edge
        self._edges: Dict[Tuple[str, str], str] = {}
        self._long_holds: List[dict] = []
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _stack(self) -> str:
        # drop the locktrace frames themselves; keep the callers
        frames = traceback.format_stack(limit=self.stack_depth)[:-2]
        return "".join(frames)

    def on_acquired(self, lock: "TracedLock") -> None:
        held = self._held()
        if held:
            stack = self._stack()
            with self._mu:
                for prev, _t0, _s in held:
                    if prev is not lock and prev.name != lock.name:
                        self._edges.setdefault(
                            (prev.name, lock.name), stack)
        held.append((lock, time.monotonic(), None))

    def on_release(self, lock: "TracedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _, t0, _ = held.pop(i)
                dur = time.monotonic() - t0
                if dur >= self.hold_threshold_s:
                    with self._mu:
                        self._long_holds.append({
                            "lock": lock.name,
                            "held_s": dur,
                            "stack": self._stack(),
                        })
                return
        # release without a recorded acquire (e.g. tracing enabled
        # mid-flight): ignore rather than corrupt the stack

    # -- analysis ------------------------------------------------------
    def edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self._edges)

    def cycles(self) -> List[List[str]]:
        """Cycles in the lock-order graph (Tarjan SCCs of size > 1,
        plus self-loops). Each is a potential deadlock: some thread
        ordering can make every participant wait on the next."""
        with self._mu:
            graph: Dict[str, set] = {}
            for a, b in self._edges:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Dict[str, bool] = {}
        stack: List[str] = []
        counter = [0]
        out: List[List[str]] = []

        def strongconnect(v: str) -> None:
            # iterative Tarjan (recursion depth is unbounded by user
            # lock graphs)
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack[v] = True
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack[w] = True
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if on_stack.get(w):
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1 or node in graph.get(node, ()):
                        out.append(sorted(scc))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return out

    def edge_stack(self, a: str, b: str) -> Optional[str]:
        with self._mu:
            return self._edges.get((a, b))

    def long_holds(self) -> List[dict]:
        with self._mu:
            return list(self._long_holds)

    def report(self) -> dict:
        return {"cycles": self.cycles(),
                "long_holds": self.long_holds(),
                "edges": self.edges()}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._long_holds.clear()


_tracer: Optional[LockTracer] = None
_tracer_mu = threading.Lock()


def get_tracer() -> LockTracer:
    global _tracer
    with _tracer_mu:
        if _tracer is None:
            threshold = float(os.environ.get(
                "RAY_TPU_LOCKTRACE_HOLD_S", "0.5"))
            _tracer = LockTracer(hold_threshold_s=threshold)
        return _tracer


def report() -> dict:
    return get_tracer().report()


def reset() -> None:
    get_tracer().reset()


class TracedLock:
    """Drop-in for ``threading.Lock``/``RLock`` that reports to a
    LockTracer. Supports the full context-manager + acquire/release
    protocol, so it also works as the lock behind a
    ``threading.Condition``."""

    def __init__(self, name: Optional[str] = None, *,
                 reentrant: bool = False,
                 tracer: Optional[LockTracer] = None):
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self.name = name or f"anon-{id(self):#x}"
        self._tracer = tracer or get_tracer()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracer.on_acquired(self)
        return got

    def release(self) -> None:
        self._tracer.on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        locked_fn = getattr(self._inner, "locked", None)
        return locked_fn() if locked_fn is not None else False

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TracedLock {self.name!r} inner={self._inner!r}>"


def traced_lock(name: str):
    """``threading.Lock()`` normally; a TracedLock under
    RAY_TPU_LOCKTRACE=1. The name is the node label in the lock-order
    graph — use a stable dotted component name, not an instance id, so
    orders observed across instances of the same class aggregate."""
    return TracedLock(name) if enabled() else threading.Lock()


def traced_rlock(name: str):
    return TracedLock(name, reentrant=True) if enabled() \
        else threading.RLock()
