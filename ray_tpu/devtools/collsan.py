"""collsan: opt-in cross-rank collective-program sanitizer.

The static half of the collective contract lives in graftlint's
GL021-GL023 (``ray_tpu/devtools/lint/rules/collectives.py``); this
module is the runtime half, in the locktrace/threadguard/refsan mold:
every host-collective entry point in ``parallel/collective.py``
(``allreduce``, ``reduce_scatter_flat``, ``allgather_flat`` /
``allgather``, ``reducescatter``, ``broadcast``, ``barrier``, the p2p
``send``/``recv`` pair) and the optimizer-level wrappers in
``train/collective.py`` stamps a per-(group, rank) monotonically
sequenced *fingerprint*

    (seq, op_kind, dtype, flat_size, shape_hash,
     compression, ef_key, algorithm)

into a per-process ledger. Worker ledgers flush to the driver over the
same control channel the flight recorder uses
(``gcs_call("collsan_push", ...)``); the driver-side ``fold()``
cross-checks fingerprints at equal seq across ranks and reports:

* **op_mismatch**          — ranks issued different collectives at the
  same seq (and the programs do not look merely reordered),
* **order_divergence**     — the per-rank programs diverge but contain
  the same ops nearby: one rank reordered/skipped a collective; the
  finding names the first diverging seq and both ranks' surrounding
  windows,
* **shape_mismatch**       — same op, different flat size / shape,
* **dtype_mismatch**       — same op, different element dtype,
* **compression_mismatch** — same op/shape, different compression,
  ``ef_key`` or algorithm (error-feedback residuals cross-contaminate),
* **missing_rank**         — a rank of the group's world never issued
  (or stopped issuing) collectives while its peers progressed; only
  judged when the caller asserts the journals are complete
  (``expect_complete=True``) so flush lag cannot fabricate it.

A **hung-collective watchdog** (driver thread, threshold
``RTPU_COLLSAN_STALL_S``, default 30s) turns today's silent
``_kv_wait`` timeout into a one-line diagnosis: which ranks are parked
inside which collective seq, and which ranks never arrived.

``verify_program(program, world)`` is the pure half: an explicit
checker for a list-of-collective-ops "program" (per-rank group-op
order equality, FIFO send/recv pairing per channel, peak-live-bytes
bound) shared by pipeline ``validate_schedule`` and targeted by the
resharding planner as its output contract.

Enable with::

    RAY_TPU_COLLSAN=1 python my_driver.py
    RAY_TPU_COLLSAN=1 RTPU_COLLSAN_STALL_S=5 pytest ...

With ``RAY_TPU_COLLSAN`` unset every hook is two loads and a compare::

    led = collsan.LEDGER
    if led is not None:
        led.record_enter(...)

Like everything in devtools, importing this module must stay cheap:
no jax, no numpy, no runtime imports.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_ENV_FLAG = "RAY_TPU_COLLSAN"
_STALL_ENV = "RTPU_COLLSAN_STALL_S"
STALL_DEFAULT_S = 30.0

#: groups with this prefix hold point-to-point ops (send/recv); their
#: programs legitimately differ across ranks, so the cross-rank order
#: fold skips them — the stall watchdog still covers a parked recv.
P2P_PREFIX = "p2p:"

#: how many fingerprints either side of the first diverging seq are
#: quoted in an order_divergence finding.
WINDOW = 3

#: how far ahead a "missing" op may reappear before a divergence is
#: classified as reordering rather than a plain op_mismatch.
_REORDER_LOOKAHEAD = 8

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "yes", "on")


def stall_threshold_s() -> float:
    try:
        return float(os.environ.get(_STALL_ENV, STALL_DEFAULT_S))
    except ValueError:
        return STALL_DEFAULT_S


def shape_hash(shape) -> int:
    """Deterministic FNV-1a over the dims — stable across processes
    (unlike ``hash`` on str-bearing values under hash randomization)."""
    h = 0xCBF29CE484222325
    for dim in shape:
        h ^= int(dim) & 0xFFFFFFFFFFFFFFFF
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h & 0xFFFFFFFF


#: str(np.dtype) costs ~7µs — memoized, it is ~0.2µs on the stamp path
#: (the set of distinct dtype objects a process reduces is tiny)
_DTYPE_STR_CACHE: Dict[Any, str] = {}


def fingerprint(op_kind: str, dtype: Any = "", flat_size: int = 0,
                shape=(), compression: Optional[str] = None,
                ef_key: Optional[str] = None,
                algorithm: Optional[str] = None) -> tuple:
    """The cross-rank comparable identity of one collective call."""
    if type(dtype) is not str:
        s = _DTYPE_STR_CACHE.get(dtype)
        if s is None:
            s = _DTYPE_STR_CACHE.setdefault(dtype, str(dtype))
        dtype = s
    return (op_kind, dtype, int(flat_size), shape_hash(shape),
            compression, ef_key, algorithm)


def _dtype_bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(str(dtype), 4)


class Ledger:
    """Per-process collective ledger. Each entry/exit appends one tuple

        (idx, kind, group, rank, world, seq, fp, t_wall)

    where ``idx`` is the process-wide push ticket, ``kind`` is
    ``"enter"``/``"exit"``, ``seq`` is the per-group logical collective
    counter and ``fp`` is the :func:`fingerprint`. ``list.append`` is
    atomic under the GIL; readers only slice the append-only list
    (flight-recorder discipline)."""

    def __init__(self, label: str = ""):
        self.label = label or f"pid:{os.getpid()}"
        self._events: List[tuple] = []
        self._idx = itertools.count()
        self._seqs: Dict[str, int] = {}

    # -- event stream ---------------------------------------------------

    def record_enter(self, group: str, rank: int, world: int,
                     fp: tuple) -> int:
        """Stamp entry into a collective; returns the seq token the
        matching :meth:`record_exit` must echo."""
        seq = self._seqs.get(group, 0)
        self._seqs[group] = seq + 1
        self._events.append((next(self._idx), "enter", group, rank,  # graftlint: disable=GL001
                             world, seq, fp, time.time()))
        return seq

    def record_exit(self, group: str, rank: int, world: int,
                    seq: int, op_kind: str) -> None:
        self._events.append((next(self._idx), "exit", group, rank,  # graftlint: disable=GL001
                             world, seq, (op_kind,), time.time()))

    def snapshot(self, since: int = 0) -> List[tuple]:
        """Events with index >= ``since`` (the list is append-only)."""
        return self._events[since:]

    def event_count(self) -> int:
        return len(self._events)


# The module-level gate. Hot paths read this once and None-check it;
# rebinding is atomic under the GIL so enable/disable race nothing.
LEDGER: Optional[Ledger] = None


def enable(label: str = "") -> Ledger:
    global LEDGER
    LEDGER = Ledger(label=label)
    return LEDGER


def disable() -> None:
    global LEDGER
    LEDGER = None


# --- driver-side collector ----------------------------------------------

class _CollsanStore:
    """Driver-held worker ledgers pushed over ``collsan_push``."""

    def __init__(self):
        self.lock = threading.Lock()
        self._procs: Dict[str, List[tuple]] = {}

    def push(self, label: str, events: List[tuple]) -> None:
        # Brief and lock-only: runs in the GCS dispatch path, which may
        # be the head's IO-loop thread.
        with self.lock:
            bucket = self._procs.setdefault(label, [])
            last = bucket[-1][0] if bucket else -1
            for ev in events:
                if ev[0] > last:
                    bucket.append(tuple(ev))
                    last = ev[0]

    def journals(self) -> Dict[str, List[tuple]]:
        with self.lock:
            return {label: list(evs)
                    for label, evs in sorted(self._procs.items())}


_STORE: Optional[_CollsanStore] = None
_final_findings: Optional[List[dict]] = None
_watchdog_findings: List[dict] = []


def get_store() -> _CollsanStore:
    global _STORE
    if _STORE is None:
        _STORE = _CollsanStore()
    return _STORE


def store_push(label: str, events: List[tuple]) -> None:
    get_store().push(label, events)


def merged_events() -> List[tuple]:
    """Every collected worker event plus the local ledger's."""
    out: List[tuple] = []
    store = _STORE
    if store is not None:
        for events in store.journals().values():
            out.extend(events)
    led = LEDGER
    if led is not None:
        out.extend(led.snapshot())
    return out


# --- process wiring ------------------------------------------------------

def init_driver() -> None:
    """Reset collector state and (when ``RAY_TPU_COLLSAN`` is set)
    enable the driver's ledger plus the stall watchdog. Called from
    ``Runtime.__init__``; the env flag rides into workers untouched."""
    global _STORE, _final_findings, _watchdog_findings
    _STORE = _CollsanStore()
    _final_findings = None
    _watchdog_findings = []
    stop_flusher()
    stop_watchdog()
    if enabled():
        enable(label=f"driver:{os.getpid()}")
        start_watchdog()
    else:
        disable()


def init_worker(rt, worker_id) -> None:
    """Enable the ledger and start the push flusher in a worker process
    (no-op unless the driver session runs with ``RAY_TPU_COLLSAN``)."""
    if not enabled():
        return
    led = enable(label=f"worker:{worker_id.hex()[:12]}:pid:{os.getpid()}")
    start_flusher(rt, led)


class _Flusher(threading.Thread):
    """Worker-side daemon: periodically push the ledger increment to
    the driver over the control channel (same route as flight_push)."""

    def __init__(self, rt, ledger: Ledger, interval_s: float = 0.25):
        super().__init__(name="collsan-flush", daemon=True)
        self._rt = rt
        self._ledger = ledger
        self._interval = max(0.02, float(interval_s))
        self._sent = 0
        self._stop = threading.Event()

    def flush_once(self) -> None:
        events = self._ledger.snapshot(since=self._sent)
        if not events:
            return
        self._rt.gcs_call("collsan_push", self._ledger.label, events)
        self._sent += len(events)

    def run(self) -> None:
        from ray_tpu.util.backoff import Backoff

        # Failed pushes back off with jitter (util/backoff.py) instead
        # of re-hammering a struggling control channel every interval.
        backoff = Backoff(initial_s=self._interval,
                          max_s=8 * self._interval)
        failures = 0
        delay = self._interval
        while not self._stop.wait(delay):
            try:
                self.flush_once()
                failures = 0
                backoff.reset()
                delay = self._interval
            except Exception:  # noqa: BLE001 — channel gone at shutdown
                failures += 1
                if failures >= 3:
                    return
                delay = backoff.next_delay()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.flush_once()  # final increment, best effort
        except Exception:  # graftlint: disable=GL004
            pass  # shutdown race: the control channel may be gone


_flusher: Optional[_Flusher] = None


def start_flusher(rt, ledger: Ledger) -> None:
    global _flusher
    _flusher = _Flusher(rt, ledger)
    _flusher.start()


def stop_flusher() -> None:
    global _flusher
    if _flusher is not None:
        _flusher.stop()
        _flusher = None


class _Watchdog(threading.Thread):
    """Driver-side daemon: periodically scan the merged journals for
    collectives some ranks entered more than ``RTPU_COLLSAN_STALL_S``
    ago and never left, and log the one-line diagnosis (which ranks
    are parked at which seq; which ranks never arrived)."""

    def __init__(self, stall_s: Optional[float] = None):
        super().__init__(name="collsan-watchdog", daemon=True)
        self.stall_s = stall_threshold_s() if stall_s is None else stall_s
        self._stop = threading.Event()
        self._reported: set = set()

    def scan_once(self, now: Optional[float] = None) -> List[dict]:
        fresh = []
        for f in stall_findings(merged_events(), stall_s=self.stall_s,
                                now=now):
            key = (f["group"], f["seq"])
            if key in self._reported:
                continue
            self._reported.add(key)
            _watchdog_findings.append(f)
            fresh.append(f)
            logger.warning("collsan: %s", f["detail"])
        return fresh

    def run(self) -> None:
        interval = max(0.25, self.stall_s / 4.0)
        while not self._stop.wait(interval):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — scan must never kill us
                logger.debug("collsan watchdog scan failed",
                             exc_info=True)

    def stop(self) -> None:
        self._stop.set()


_watchdog: Optional[_Watchdog] = None


def start_watchdog(stall_s: Optional[float] = None) -> _Watchdog:
    global _watchdog
    _watchdog = _Watchdog(stall_s=stall_s)
    _watchdog.start()
    return _watchdog


def stop_watchdog() -> None:
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


# --- the fold -------------------------------------------------------------

def _programs(events: List[tuple]
              ) -> Dict[str, Dict[int, List[tuple]]]:
    """group -> rank -> seq-sorted list of enter events."""
    out: Dict[str, Dict[int, List[tuple]]] = {}
    for ev in events:
        if ev[1] != "enter":
            continue
        out.setdefault(ev[2], {}).setdefault(ev[3], []).append(ev)
    for ranks in out.values():
        for evs in ranks.values():
            evs.sort(key=lambda e: e[5])
    return out


def _window(evs: List[tuple], seq: int) -> List[str]:
    lo, hi = seq - WINDOW, seq + WINDOW
    return [f"seq {e[5]}: {e[6][0]}" for e in evs if lo <= e[5] <= hi]


def _mismatch(group: str, seq: int, ref_ev: tuple, ev: tuple,
              kind: str, what: str) -> dict:
    r0, r1 = ref_ev[3], ev[3]
    return {"kind": kind, "group": group, "seq": seq,
            "ranks": sorted((r0, r1)),
            "detail": f"group '{group}' seq {seq}: {what} — "
                      f"rank {r0} issued {ref_ev[6]!r}, "
                      f"rank {r1} issued {ev[6]!r}"}


def fold(events: List[tuple],
         expect_complete: bool = False) -> List[dict]:
    """Cross-check the merged fingerprint stream. Each finding is a
    dict ``{"kind", "group", "seq", "ranks", "detail"}``.

    ``expect_complete=True`` asserts every rank's journal is final
    (synthetic fixtures, post-barrier folds): only then are shorter or
    absent per-rank programs reported as ``missing_rank`` — a live
    fold must not read flush lag as a vanished rank."""
    findings: List[dict] = []
    for group, ranks in sorted(_programs(events).items()):
        if group.startswith(P2P_PREFIX):
            continue  # p2p programs legitimately differ across ranks
        world = max((ev[4] for evs in ranks.values() for ev in evs),
                    default=0)
        if expect_complete and world > len(ranks):
            peak = max(ev[5] for evs in ranks.values() for ev in evs)
            for rank in range(world):
                if rank not in ranks:
                    findings.append({
                        "kind": "missing_rank", "group": group,
                        "seq": 0, "ranks": [rank],
                        "detail": f"group '{group}': rank {rank} never "
                                  f"issued a collective while peers "
                                  f"reached seq {peak}"})
        ordered = sorted(ranks)
        ref = ordered[0]
        ref_evs = ranks[ref]
        ref_by_seq = {ev[5]: ev for ev in ref_evs}
        for rank in ordered[1:]:
            evs = ranks[rank]
            diverged = False
            for ev in evs:
                seq = ev[5]
                ref_ev = ref_by_seq.get(seq)
                if ref_ev is None or ref_ev[6] == ev[6]:
                    continue
                rfp, fp = ref_ev[6], ev[6]
                if rfp[0] != fp[0]:
                    # op kinds differ: reordered program, or flatly
                    # different ops at this slot?
                    near = [e[6][0] for e in evs
                            if seq < e[5] <= seq + _REORDER_LOOKAHEAD]
                    ref_near = [e[6][0] for e in ref_evs
                                if seq < e[5] <= seq + _REORDER_LOOKAHEAD]
                    if rfp[0] in near or fp[0] in ref_near:
                        findings.append({
                            "kind": "order_divergence", "group": group,
                            "seq": seq, "ranks": sorted((ref, rank)),
                            "detail": (
                                f"group '{group}': programs of rank "
                                f"{ref} and rank {rank} diverge at seq "
                                f"{seq} ({rfp[0]} vs {fp[0]}); rank "
                                f"{ref} window: {_window(ref_evs, seq)}; "
                                f"rank {rank} window: "
                                f"{_window(evs, seq)}")})
                    else:
                        findings.append(_mismatch(
                            group, seq, ref_ev, ev, "op_mismatch",
                            "different collectives at the same seq"))
                    diverged = True
                    break  # everything after the first op-level
                    # divergence is cascade noise for this pair
                elif rfp[1] != fp[1]:
                    findings.append(_mismatch(
                        group, seq, ref_ev, ev, "dtype_mismatch",
                        "same op, different dtype"))
                elif rfp[2] != fp[2] or rfp[3] != fp[3]:
                    findings.append(_mismatch(
                        group, seq, ref_ev, ev, "shape_mismatch",
                        "same op, different tensor shape"))
                else:
                    findings.append(_mismatch(
                        group, seq, ref_ev, ev, "compression_mismatch",
                        "same op/shape, different compression, ef_key "
                        "or algorithm"))
            if expect_complete and not diverged:
                peak = max(e[5] for e in ref_evs + evs)
                short, other = ((rank, ref)
                                if evs[-1][5] < ref_evs[-1][5]
                                else (ref, rank))
                if ranks[short][-1][5] < peak:
                    findings.append({
                        "kind": "missing_rank", "group": group,
                        "seq": ranks[short][-1][5] + 1,
                        "ranks": [short],
                        "detail": f"group '{group}': rank {short} "
                                  f"stopped after seq "
                                  f"{ranks[short][-1][5]} while rank "
                                  f"{other} reached seq {peak}"})
    return findings


def stall_findings(events: List[tuple],
                   stall_s: Optional[float] = None,
                   now: Optional[float] = None) -> List[dict]:
    """Collectives some rank entered more than ``stall_s`` ago and
    never exited: the hung-collective diagnosis. One finding per
    (group, seq) names the parked ranks (with their op) and the ranks
    that never arrived."""
    stall_s = stall_threshold_s() if stall_s is None else stall_s
    now = time.time() if now is None else now
    open_enters: Dict[Tuple[str, int], Dict[int, tuple]] = {}
    exits: set = set()
    last_seq: Dict[Tuple[str, int], int] = {}
    world_of: Dict[str, int] = {}
    for ev in events:
        _idx, kind, group, rank, world, seq, _fp, _t = ev
        world_of[group] = max(world_of.get(group, 0), world)
        if kind == "enter":
            open_enters.setdefault((group, seq), {})[rank] = ev
            key = (group, rank)
            last_seq[key] = max(last_seq.get(key, -1), seq)
        elif kind == "exit":
            exits.add((group, seq, rank))
    findings: List[dict] = []
    for (group, seq), entered in sorted(open_enters.items()):
        parked = {rank: ev for rank, ev in entered.items()
                  if (group, seq, rank) not in exits
                  and now - ev[7] >= stall_s}
        if not parked:
            continue
        age = max(now - ev[7] for ev in parked.values())
        missing = [r for r in range(world_of.get(group, 0))
                   if r not in entered
                   and last_seq.get((group, r), -1) < seq]
        ops = sorted({ev[6][0] for ev in parked.values()})
        detail = (f"group '{group}' seq {seq}: rank(s) "
                  f"{sorted(parked)} parked inside "
                  f"{'/'.join(ops)} for {age:.1f}s")
        if missing:
            detail += f"; rank(s) {missing} never arrived"
        findings.append({
            "kind": "stall", "group": group, "seq": seq,
            "ranks": sorted(parked), "missing": missing,
            "ops": ops, "age_s": round(age, 3),
            "parked_since": min(ev[7] for ev in parked.values()),
            "detail": detail})
    return findings


def report(expect_complete: bool = False) -> List[dict]:
    """Fold the merged journals into findings — cross-rank mismatches
    plus currently stalled collectives plus anything the watchdog or a
    shutdown-time fold already caught. Empty when collsan is off."""
    if LEDGER is None and _STORE is None:
        return list(_final_findings or [])
    events = merged_events()
    findings = fold(events, expect_complete=expect_complete)
    seen = {(f["kind"], f["group"], f["seq"]) for f in findings}
    for f in stall_findings(events) + _watchdog_findings + list(
            _final_findings or []):
        key = (f["kind"], f["group"], f["seq"])
        if key not in seen:
            seen.add(key)
            findings.append(f)
    return findings


def on_shutdown() -> None:
    """Runtime shutdown hook: fold once while worker journals are
    still current, and keep the result for late ``report()`` calls
    (the ledger itself is torn down with the session)."""
    global _final_findings, _STORE
    stop_watchdog()
    if LEDGER is None:
        return
    findings = report()
    _final_findings = findings
    disable()
    _STORE = None
    for f in findings:
        logger.warning("collsan: %s group=%s seq=%s: %s",
                       f["kind"], f["group"], f["seq"], f["detail"])


def format_findings(findings: List[dict]) -> str:
    return "\n".join(
        f"collsan: {f['kind']} group={f['group']} seq={f['seq']}: "
        f"{f['detail']}" for f in findings)


# --- capture (profdiff input) --------------------------------------------

def capture(events: Optional[List[tuple]] = None) -> Dict[str, Any]:
    """Fold dump for ``profdiff``: per-group collective call counts
    and traffic, auto-detected by ``profdiff.normalize`` the same way
    phase tables are."""
    events = merged_events() if events is None else events
    groups: Dict[str, Dict[str, Dict[str, int]]] = {}
    for ev in events:
        if ev[1] != "enter":
            continue
        _idx, _kind, group, _rank, _world, _seq, fp, _t = ev
        ops = groups.setdefault(group, {})
        row = ops.setdefault(fp[0], {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += int(fp[2]) * _dtype_bytes(fp[1])
    return {"kind": "rtpu-collsan", "groups": groups}


# --- the pure program checker --------------------------------------------

def verify_program(program: Dict[int, List[dict]],
                   world: Optional[int] = None,
                   max_live_bytes=None) -> List[str]:
    """Pure checker for an explicit multi-rank collective program.

    ``program`` maps rank -> ordered op list; each op is a dict:

    * group-wide collective: ``{"op": "allreduce"|..., "key": any}`` —
      the ``(op, key)`` sequence must be identical on every rank,
    * point-to-point: ``{"op": "send"/"recv", "chan": hashable,
      "key": any}`` — per channel, the send key order must equal the
      recv key order (FIFO pairing),
    * memory: ``{"op": "alloc"/"free", "bytes": int}`` — per rank,
      peak live bytes must stay within ``max_live_bytes`` (an int, or
      a rank -> int mapping).

    Returns a list of violation strings; empty means the program is a
    valid single-program-multiple-rank collective schedule. This is
    the contract ``pipeline.schedule.validate_schedule`` checks its
    schedules against and the resharding planner will emit into.
    """
    violations: List[str] = []
    ranks = sorted(program)
    if world is not None:
        for r in range(world):
            if r not in program:
                violations.append(f"rank {r} missing from program "
                                  f"(world {world})")
        for r in ranks:
            if not 0 <= r < world:
                violations.append(f"rank {r} outside world {world}")
        ranks = [r for r in ranks if 0 <= r < world]

    def _sig(rank: int) -> List[tuple]:
        return [(op.get("op"), op.get("key")) for op in program[rank]
                if op.get("op") not in ("send", "recv", "alloc", "free")]

    if ranks:
        ref = ranks[0]
        ref_sig = _sig(ref)
        for r in ranks[1:]:
            sig = _sig(r)
            if sig == ref_sig:
                continue
            n = min(len(sig), len(ref_sig))
            i = next((k for k in range(n) if sig[k] != ref_sig[k]), n)
            a = ref_sig[i] if i < len(ref_sig) else "<end>"
            b = sig[i] if i < len(sig) else "<end>"
            violations.append(
                f"group-op order diverges between rank {ref} and rank "
                f"{r} at op #{i}: {a!r} vs {b!r}")

    sends: Dict[Any, List[Any]] = {}
    recvs: Dict[Any, List[Any]] = {}
    for r in ranks:
        for op in program[r]:
            if op.get("op") == "send":
                sends.setdefault(op.get("chan"), []).append(op.get("key"))
            elif op.get("op") == "recv":
                recvs.setdefault(op.get("chan"), []).append(op.get("key"))
    for chan in sorted(set(sends) | set(recvs), key=repr):
        s, v = sends.get(chan, []), recvs.get(chan, [])
        if s != v:
            violations.append(
                f"chan {chan!r}: unpaired or reordered send/recv "
                f"(sends {s} vs recvs {v})")

    if max_live_bytes is not None:
        for r in ranks:
            bound = (max_live_bytes.get(r)
                     if isinstance(max_live_bytes, dict)
                     else max_live_bytes)
            if bound is None:
                continue
            live = peak = 0
            for op in program[r]:
                if op.get("op") == "alloc":
                    live += int(op.get("bytes", 0))
                    peak = max(peak, live)
                elif op.get("op") == "free":
                    live -= int(op.get("bytes", 0))
            if peak > bound:
                violations.append(
                    f"rank {r}: peak live bytes {peak} exceeds bound "
                    f"{bound}")
    return violations
