"""One-shot local quality gate: ``python -m ray_tpu.devtools.check``.

Runs, in order, everything a reviewer would otherwise run by hand:

1. **lint** — graftlint over ``ray_tpu/`` against the checked-in
   baseline (``graftlint_baseline.json``).
2. **locktrace** — a tiny end-to-end smoke run (init, tasks, put/get,
   shutdown) in a subprocess with ``RAY_TPU_LOCKTRACE=1``; fails on
   any detected lock-order cycle.
3. **threadguard** — the same smoke run with ``RAY_TPU_THREADGUARD=1``
   and an aggressive stall threshold; fails on any ``@loop_only``
   affinity violation (raises in-run) or watchdog stall report.
4. **refsan** — the object-lifetime sanitizer's fold over a seeded
   leak/double-release fixture (must fire), then the smoke run with
   ``RAY_TPU_REFSAN=1`` (must report zero ledger findings).
5. **chaos** — an 8-virtual-node drill (core/virtual_node.py +
   devtools/chaos.py): one seeded node kill mid-fanout; every task
   must still complete and the recovery report must fold exactly one
   incident attributed to the injected fault.
6. **stress** — the native shm stress binary, plain plus ASan/TSan
   variants when the toolchain on this image can link them; each
   missing sanitizer is a clean SKIP, not a failure.

Every step prints ``ok`` / ``SKIP (reason)`` / ``FAIL`` and the
command exits non-zero iff any step failed. ``--only STEP`` runs a
single step (e.g. ``--only lint`` for the fast pre-commit path).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time
from typing import Callable, List, Optional, Tuple

# The smoke driver exercised under locktrace/threadguard. Kept as a
# string so it runs in a pristine subprocess: the instrumented env
# vars must be set before ray_tpu (and its locks/loops) are imported.
_SMOKE_SRC = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import ray_tpu

ray_tpu.init(num_cpus=2,
             system_config={"task_max_retries": 0})

@ray_tpu.remote
def add(a, b):
    return a + b

refs = [add.remote(i, i) for i in range(20)]
assert ray_tpu.get(refs) == [2 * i for i in range(20)]
blob = ray_tpu.put(b"x" * 100_000)
assert len(ray_tpu.get(blob)) == 100_000
ray_tpu.shutdown()

mode = sys.argv[1]
if mode == "refsan":
    from ray_tpu.devtools import refsan
    findings = refsan.report()
    if findings:
        print(refsan.format_findings(findings))
        sys.exit(3)
elif mode == "locktrace":
    from ray_tpu.devtools import locktrace
    rep = locktrace.report()
    if rep.get("cycles"):
        print("CYCLES:", rep["cycles"])
        sys.exit(3)
elif mode == "threadguard":
    from ray_tpu.devtools import threadguard
    reports = threadguard.stall_reports()
    if reports:
        for r in reports:
            print("STALL %.3fs on %s\n%s" %
                  (r["stalled_s"], r["thread"], r["stack"]))
        sys.exit(3)
print("SMOKE-OK")
"""


def _run_smoke(mode: str, extra_env: dict) -> Tuple[bool, str]:
    env = dict(os.environ)
    env.update(extra_env)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # the smoke script lives in /tmp — make sure the repo providing
    # this module stays importable from there
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    with tempfile.NamedTemporaryFile(
            "w", suffix="_rtpu_smoke.py", delete=False) as f:
        f.write(_SMOKE_SRC)
        path = f.name
    try:
        proc = subprocess.run(
            [sys.executable, path, mode], env=env,
            capture_output=True, text=True, timeout=180)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    out = (proc.stdout or "") + (proc.stderr or "")
    ok = proc.returncode == 0 and "SMOKE-OK" in proc.stdout
    return ok, out


# --- steps ---------------------------------------------------------------

def step_lint() -> Tuple[str, str]:
    """graftlint over ray_tpu/ against the default baseline."""
    from ray_tpu.devtools import lint
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    findings = lint.lint_paths([os.path.join(root, "ray_tpu")])
    baseline_path = lint.find_default_baseline(
        [os.path.join(root, "ray_tpu")])
    if baseline_path:
        baseline = lint.load_baseline(baseline_path)
        findings = lint.apply_baseline(findings, baseline)
    if findings:
        lines = [f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}"
                 for f in findings]
        return "FAIL", "\n".join(lines)
    return "ok", ""


def step_locktrace() -> Tuple[str, str]:
    """End-to-end smoke under RAY_TPU_LOCKTRACE=1; no lock cycles."""
    ok, out = _run_smoke("locktrace", {"RAY_TPU_LOCKTRACE": "1"})
    return ("ok", "") if ok else ("FAIL", out[-4000:])


def step_threadguard() -> Tuple[str, str]:
    """Smoke under RAY_TPU_THREADGUARD=1; no affinity errors/stalls."""
    ok, out = _run_smoke("threadguard", {
        "RAY_TPU_THREADGUARD": "1",
        "RAY_TPU_THREADGUARD_STALL_S": "0.5",
    })
    return ("ok", "") if ok else ("FAIL", out[-4000:])


def _gxx_probe(extra_flags: List[str]) -> bool:
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "probe.cc")
        with open(src, "w") as f:
            f.write("int main(){return 0;}\n")
        try:
            proc = subprocess.run(
                ["g++", *extra_flags, "-o", os.path.join(d, "probe"),
                 src], capture_output=True)
        except OSError:
            return False
        return proc.returncode == 0


def _sanitizer_available(kind: str) -> bool:
    return _gxx_probe([f"-fsanitize={kind}"])


def _run_stress(sanitize: Optional[str], mode: str, workers: int,
                iters: int) -> Tuple[bool, str]:
    from ray_tpu.native.build import build_stress
    try:
        binary = build_stress(sanitize) if sanitize else build_stress()
    except Exception as exc:  # toolchain missing → caller SKIPs
        return False, f"build failed: {exc}"
    proc = subprocess.run(
        [binary, mode, str(workers), str(iters)],
        capture_output=True, text=True, timeout=300)
    ok = proc.returncode == 0 and "STRESS-OK" in proc.stdout
    detail = "" if ok else (
        f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr[-3000:]}")
    return ok, detail


def step_stress() -> Tuple[str, str]:
    """Native shm stress: plain always; ASan/TSan when linkable."""
    try:
        from ray_tpu.native.build import build_stress  # noqa: F401
    except Exception as exc:
        return "SKIP", f"native build unavailable: {exc}"
    if not _gxx_probe([]):
        return "SKIP", "no working g++ on this image"
    ok, detail = _run_stress(None, "threads", workers=6, iters=150)
    if not ok:
        return "FAIL", detail
    notes = []
    for kind in ("address", "thread"):
        if not _sanitizer_available(kind):
            notes.append(f"{kind}: SKIP (sanitizer unavailable)")
            continue
        ok, detail = _run_stress(kind, "threads", workers=4, iters=80)
        if not ok:
            return "FAIL", f"[{kind}] {detail}"
        notes.append(f"{kind}: ok")
    return "ok", "; ".join(notes)


def step_pipeline() -> Tuple[str, str]:
    """Pipeline-schedule smoke: golden-validate the static 1F1B/GPipe
    instruction lists over a spread of (stages, microbatches) shapes.
    Pure scheduler math — no actors, no channels, no jax."""
    try:
        from ray_tpu.train.pipeline import schedule as sched
    except Exception as exc:
        return "FAIL", f"pipeline schedule import failed: {exc!r}"
    shapes = [(2, 2), (2, 8), (3, 4), (3, 8), (4, 4), (4, 16), (6, 6),
              (8, 32)]
    checked = 0
    for stages, microbatches in shapes:
        for name in sched.SCHEDULES:
            try:
                sched.validate_schedule(stages, microbatches, name)
                # 1F1B must never hold more activations than warmup
                # depth; GPipe holds all M during fill
                bound = (sched.warmup_depth(0, stages, microbatches)
                         if name == "1f1b" else microbatches)
                worst = max(
                    sched.max_in_flight(sched.stage_schedule(
                        s, stages, microbatches, name))
                    for s in range(stages))
                if worst > bound:
                    return "FAIL", (
                        f"{name} (s={stages}, m={microbatches}): "
                        f"max in-flight {worst} exceeds bound {bound}")
            except Exception as exc:
                return "FAIL", (f"{name} (s={stages}, "
                                f"m={microbatches}): {exc!r}")
            checked += 1
    return "ok", f"{checked} schedule shapes validated"


def step_recorder() -> Tuple[str, str]:
    """Flight-recorder smoke, fully in-process: record events into a
    fresh ring, push a fake worker journal with a known clock offset,
    merge, export Chrome-trace events, and parse the JSON round-trip."""
    import json as _json
    from ray_tpu.util import flight_recorder as fr
    saved = (fr.RECORDER, fr._STORE)
    try:
        fr._STORE = fr.FlightStore()
        rec = fr.enable("driver:check", capacity=64)
        for i in range(8):
            t0 = fr.clock_ns()
            rec.record("io", "dispatch", t0, 1_000, {"i": i})
        # a fake worker whose clock runs 5ms behind the driver's
        fr.store_push("worker:check", [(0, fr.clock_ns() - 5_000_000,
                                        2_000, "pipeline", "FWD",
                                        {"stage": 0})], 5_000_000)
        merged = fr.merged_journals()
        if set(merged) != {"driver:check", "worker:check"}:
            return "FAIL", f"merge lost a journal: {sorted(merged)}"
        payload = _json.loads(_json.dumps(fr.chrome_events()))
        meta = [ev for ev in payload if ev["ph"] == "M"]
        events = [ev for ev in payload if ev["ph"] != "M"]
        if len(events) != 9:
            return "FAIL", f"expected 9 trace events, got {len(events)}"
        # each track must lead with role-naming metadata (PR 18)
        named = {ev["pid"] for ev in meta if ev["name"] == "process_name"}
        if named != {"flight:driver:check", "flight:worker:check"}:
            return "FAIL", f"tracks missing process_name meta: {named}"
        for ev in events:
            if not {"name", "ph", "ts", "pid", "tid"} <= set(ev):
                return "FAIL", f"malformed trace event: {ev}"
            if ev["ph"] == "X" and not isinstance(ev["dur"], (int, float)):
                return "FAIL", f"X event without numeric dur: {ev}"
        return "ok", (f"{len(events)} events + {len(meta)} metadata "
                      f"across 2 journals")
    finally:
        fr.RECORDER, fr._STORE = saved


def step_profile() -> Tuple[str, str]:
    """Perf-observatory smoke, fully in-process: (1) the sampling
    profiler over a seeded busy loop must attribute ≥50% of this
    thread's samples to it; (2) the whereis task-path fold over a
    synthetic phase journal must reproduce its known µs table exactly
    (coverage 1.0 — the chain is contiguous by construction)."""
    import sys as _sys
    if not hasattr(_sys, "_current_frames"):
        return "SKIP", "platform lacks sys._current_frames"
    import threading as _threading
    import time as _time
    from ray_tpu.devtools import profiler
    from ray_tpu.devtools import whereis as whereis_mod

    # (1) sampler attribution: burn CPU in THIS frame while a fast
    # sampler watches; our role's samples must mostly land here.
    sampler = profiler.Sampler("driver:check", hz=250)
    sampler.start()
    deadline = _time.monotonic() + 0.4
    x = 0
    while _time.monotonic() < deadline:
        for i in range(5000):
            x += i * i
    sampler.stop()
    sampler.join(timeout=2.0)
    role = profiler._role(_threading.current_thread().name)
    mine = total = 0
    for stack, n in sampler.counts.items():
        if not stack.startswith(role + ";"):
            continue
        total += n
        if "step_profile" in stack:
            mine += n
    if total == 0:
        return "FAIL", f"sampler took no samples of role {role!r}"
    frac = mine / total
    if frac < 0.5:
        return "FAIL", (f"busy function got {frac:.0%} of {total} "
                        f"samples (need >=50%)")

    # (2) phase fold: contiguous synthetic chain with a known table
    base = 1_000_000_000
    spans = [("arg-serialize", 80_000), ("spec-build", 120_000),
             ("scheduler-queue", 500_000), ("lease-dispatch", 30_000),
             ("frame-encode", 40_000), ("wire-write", 25_000),
             ("worker-pickup", 200_000), ("execute", 50_000),
             ("result-return", 90_000)]
    events, t = [], base
    for seq, (name, dur) in enumerate(spans):
        events.append((seq, t, dur, "task_phase", name, {"task": "ab"}))
        t += dur
    report = whereis_mod.task_path_attribution({"driver:check": events})
    for name, dur in spans:
        got = report["phases"][name]["mean_us"]
        if abs(got - dur / 1e3) > 1e-6:
            return "FAIL", (f"phase {name}: folded mean {got}us != "
                            f"{dur / 1e3}us")
    if report["coverage"] != 1.0:
        return "FAIL", f"contiguous chain coverage {report['coverage']}"
    if report["tasks_sampled"] != 1:
        return "FAIL", f"tasks_sampled {report['tasks_sampled']} != 1"
    total_us = sum(d for _, d in spans) / 1e3
    if abs(report["mean_chain_us"] - total_us) > 0.1:
        return "FAIL", (f"chain total {report['mean_chain_us']}us != "
                        f"{total_us}us")
    return "ok", (f"sampler: {frac:.0%} of {total} samples on the busy "
                  f"fn; phase fold reproduced {len(spans)}-row table")


def step_events() -> Tuple[str, str]:
    """Recovery-timeline fold smoke, fully in-process: a synthetic
    lifecycle event stream with known phase durations (heartbeat miss →
    node death → retry → lease grant → reconstruction) must fold into
    ONE incident with exactly those durations and the full causal
    chain; an idle DEBUG worker reclaim must not root an incident."""
    from ray_tpu.devtools import recovery

    t0 = 1000.0
    ev = [
        {"seq": 1, "timestamp": t0 - 3.0, "severity": "WARNING",
         "kind": "NODE_HEARTBEAT_MISS", "node_id": "n1",
         "message": "last heartbeat 2.0s ago", "caused_by": None},
        {"seq": 2, "timestamp": t0, "severity": "ERROR",
         "kind": "NODE_DEAD", "node_id": "n1", "caused_by": 1,
         "data": {"detect_s": 3.0}},
        {"seq": 3, "timestamp": t0 + 0.1, "severity": "ERROR",
         "kind": "WORKER_EXIT", "worker_id": "w1", "caused_by": 2},
        {"seq": 4, "timestamp": t0 + 0.2, "severity": "WARNING",
         "kind": "TASK_RETRY", "task_id": "t1", "caused_by": 2},
        {"seq": 5, "timestamp": t0 + 1.5, "severity": "INFO",
         "kind": "LEASE_GRANTED", "task_id": "t1", "node_id": "n2",
         "caused_by": 4, "data": {"reschedule_s": 1.5}},
        {"seq": 6, "timestamp": t0 + 1.6, "severity": "WARNING",
         "kind": "RECONSTRUCT_START", "caused_by": 2,
         "data": {"oid": "aa" * 8}},
        {"seq": 7, "timestamp": t0 + 4.1, "severity": "INFO",
         "kind": "RECONSTRUCT_DONE", "caused_by": 6,
         "data": {"oid": "aa" * 8, "reconstruct_s": 2.5}},
        # idle reclaim: DEBUG, nothing chained — must NOT be an incident
        {"seq": 8, "timestamp": t0 + 5.0, "severity": "DEBUG",
         "kind": "WORKER_EXIT", "worker_id": "w9", "caused_by": None},
    ]
    report = recovery.recovery_report(events=ev, journals={})
    incs = report["incidents"]
    if len(incs) != 1:
        return "FAIL", (f"expected 1 incident, got {len(incs)} "
                        f"(idle reclaim must not root one)")
    inc = incs[0]
    want = {"root_kind": "NODE_DEAD", "detect_s": 3.0,
            "reschedule_s": 1.5, "reconstruct_s": 2.5,
            "mttr_s": 3.0 + 4.1}
    for key, expect in want.items():
        got = inc[key]
        if isinstance(expect, float):
            if abs(got - expect) > 1e-6:
                return "FAIL", f"{key}: expected {expect}, got {got}"
        elif got != expect:
            return "FAIL", f"{key}: expected {expect}, got {got}"
    if {e["seq"] for e in inc["chain"]} != {2, 3, 4, 5, 6, 7}:
        return "FAIL", (f"causal chain wrong: "
                        f"{sorted(e['seq'] for e in inc['chain'])}")
    if (inc["precursor"] or {}).get("kind") != "NODE_HEARTBEAT_MISS":
        return "FAIL", f"precursor not attributed: {inc['precursor']}"
    if inc["affected"]["objects"] != ["aa" * 8]:
        return "FAIL", f"affected objects wrong: {inc['affected']}"
    recovery.render(report)  # must not raise
    return "ok", ("1 incident folded: detect 3.0s, reschedule 1.5s, "
                  "reconstruct 2.5s, MTTR 7.1s, 6-event chain")


def step_podracer() -> Tuple[str, str]:
    """Podracer RL smoke, fully in-process (no actors, no cluster): the
    replay queue's bounded drop-oldest semantics, the int8 weight-push
    wire format round trip, and one fused Anakin update on the default
    backend."""
    import numpy as np
    from ray_tpu.rl.podracer import (
        Anakin, AnakinConfig, FragmentReplay, dequantize_params,
        quantize_params)

    q = FragmentReplay(capacity=4)
    for i in range(7):
        q.push(i)
    st = q.stats()
    if st["depth"] != 4 or st["dropped"] != 3:
        return "FAIL", f"replay backpressure broken: {st}"
    if q.pop_many(99) != [3, 4, 5, 6]:
        return "FAIL", "replay did not keep the freshest fragments"

    trainer = Anakin(AnakinConfig(num_envs_per_device=4, rollout_len=4,
                                  hidden=(8,)))
    out = trainer.train(1)
    if not np.isfinite(out["total_loss"]):
        return "FAIL", f"anakin update non-finite: {out}"

    params = trainer.params
    rebuilt = dequantize_params(params, quantize_params(params))
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(rebuilt)):
        scale = max(float(np.abs(a).max()), 1e-6)
        if float(np.abs(np.asarray(a) - np.asarray(b)).max()) / scale \
                > 0.02:
            return "FAIL", "int8 weight round trip exceeded 2% error"
    return "ok", (f"replay bounded at 4, anakin loss "
                  f"{out['total_loss']:.3f}, weight wire <2% err")


def step_refsan() -> Tuple[str, str]:
    """Object-lifetime sanitizer: the fold must flag a seeded
    leak/double-release fixture (in-process, synthetic events), and a
    clean end-to-end smoke under RAY_TPU_REFSAN=1 must report zero
    ledger findings."""
    from ray_tpu.devtools import refsan

    # -- seeded fixture: the detector itself must fire -------------------
    label = "check:seeded"
    seeded = [
        # oid "aa": pinned once, never released, no live view → leak
        (0, "aa" * 8, label, refsan.KIND_SLOT_PIN, 0, {"store": "s"}),
        # oid "bb": released with no pin outstanding → double release
        (1, "bb" * 8, label, refsan.KIND_SLOT_RELEASE, 0, {"store": "s"}),
    ]
    kinds = sorted(f["kind"] for f in refsan.fold(
        seeded, live_views={}, local_label=label))
    if kinds != ["double_release", "leaked_pin"]:
        return "FAIL", (f"seeded fixture misfolded: expected "
                        f"[double_release, leaked_pin], got {kinds}")

    # -- clean smoke: a correct workload must stay quiet -----------------
    ok, out = _run_smoke("refsan", {"RAY_TPU_REFSAN": "1",
                                    "RAY_TPU_REFSAN_CANARY": "1"})
    if not ok:
        return "FAIL", out[-4000:]
    return "ok", "seeded fixture fired; clean smoke reported 0 findings"


# Collective-sanitizer smoke: a 3-rank actor group runs a clean
# multi-op collective program under RAY_TPU_COLLSAN=1; after the
# journals flush, the driver-side fold must report zero findings.
_COLLSAN_SRC = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import ray_tpu
from ray_tpu.devtools import collsan

ray_tpu.init(num_cpus=4)
try:
    WORLD = 3

    @ray_tpu.remote(num_cpus=0)
    class Member:
        def __init__(self, rank):
            from ray_tpu.parallel import collective
            self.rank = rank
            collective.init_collective_group(WORLD, rank, "csan-smoke")

        def rounds(self):
            from ray_tpu.parallel import collective
            x = np.arange(64, dtype=np.float32) + self.rank
            s = collective.allreduce(x, "sum", "csan-smoke")
            shard, off = collective.reduce_scatter_flat(
                x, "sum", "csan-smoke")
            full = collective.allgather_flat(shard, "csan-smoke")
            collective.barrier("csan-smoke")
            b = collective.broadcast(x if self.rank == 0 else
                                     np.zeros(64, np.float32),
                                     src_rank=0, group_name="csan-smoke")
            collective.destroy_collective_group("csan-smoke")
            return float(s.sum() + full.sum() + b.sum())

    members = [Member.remote(r) for r in range(WORLD)]
    vals = ray_tpu.get([m.rounds.remote() for m in members], timeout=90)
    assert len(set(vals)) == 1, f"ranks disagree: {vals}"
    time.sleep(1.0)  # let the worker flushers push the final journals
    findings = collsan.report()
    if findings:
        print(collsan.format_findings(findings))
        sys.exit(3)
    assert collsan.merged_events(), "no fingerprints reached the driver"
    print("COLLSAN-OK")
finally:
    ray_tpu.shutdown()
"""


def step_collsan() -> Tuple[str, str]:
    """Collective sanitizer: the fold must flag a seeded 4-rank
    order-divergence fixture at the known seq (in-process, synthetic
    events), and a clean 3-rank collective smoke under
    RAY_TPU_COLLSAN=1 must report zero findings."""
    from ray_tpu.devtools import collsan

    # -- seeded fixture: the detector itself must fire -------------------
    def ev(idx, rank, seq, op):
        return (idx, "enter", "g", rank, 4, seq,
                collsan.fingerprint(op, "float32", 64, (64,)), 0.0)

    fixture = []
    for rank in range(4):
        # rank 3 swaps barrier/broadcast at seqs 1-2: one divergence
        ops = (["allreduce", "broadcast", "barrier"] if rank == 3
               else ["allreduce", "barrier", "broadcast"])
        for seq, op in enumerate(ops):
            fixture.append(ev(len(fixture), rank, seq, op))
    findings = collsan.fold(fixture, expect_complete=True)
    if [(f["kind"], f["seq"]) for f in findings] \
            != [("order_divergence", 1)]:
        return "FAIL", (f"seeded fixture misfolded: expected one "
                        f"order_divergence at seq 1, got {findings}")

    # -- clean smoke: a correct workload must stay quiet -----------------
    env = dict(os.environ)
    env["RAY_TPU_COLLSAN"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    with tempfile.NamedTemporaryFile(
            "w", suffix="_rtpu_collsan.py", delete=False) as f:
        f.write(_COLLSAN_SRC)
        path = f.name
    try:
        proc = subprocess.run(
            [sys.executable, path], env=env,
            capture_output=True, text=True, timeout=180)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    out = (proc.stdout or "") + (proc.stderr or "")
    if proc.returncode != 0 or "COLLSAN-OK" not in proc.stdout:
        return "FAIL", out[-4000:]
    return "ok", ("seeded order-divergence folded at seq 1; clean "
                  "3-rank smoke reported 0 findings")


# Chaos drill smoke: 8 virtual nodes, a sustained fan-out, one SEEDED
# node kill landing mid-flight. Asserts every task still completes
# (retry/reconstruction), the recovery report folds exactly one
# NODE_DEAD incident, and that incident's precursor is the injected
# CHAOS_INJECTED event (causal attribution end to end). Actor-free.
_CHAOS_SRC = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import ray_tpu
from ray_tpu.core.cluster_utils import Cluster
from ray_tpu.devtools.chaos import ChaosSchedule, ChaosController
from ray_tpu.devtools import recovery

cluster = Cluster(system_config={"head_port": 0})
try:
    cluster.add_virtual_nodes(8, resources={"CPU": 1.0})
    pool = cluster.virtual_pool

    @ray_tpu.remote
    def produce(i):
        time.sleep(0.05)
        return i * 3

    @ray_tpu.remote
    def consume(x):
        return x + 1

    refs = [consume.remote(produce.remote(i)) for i in range(64)]
    sched = ChaosSchedule.from_seed(
        7, n_targets=8, duration_s=0.3, kills=1, start_s=0.15)
    ctrl = ChaosController(cluster.runtime, sched,
                           targets=pool.live_nodes())
    ctrl.run_sync()
    assert len(ctrl.injected) == 1, ctrl.injected

    got = ray_tpu.get(refs, timeout=90)
    assert got == [i * 3 + 1 for i in range(64)], "lost results"

    report = recovery.recovery_report()
    incs = [i for i in report["incidents"]
            if i["root_kind"] == "NODE_DEAD"]
    assert len(incs) == 1, (
        f"expected one NODE_DEAD incident, got {len(incs)}")
    pre = incs[0]["precursor"] or {}
    assert pre.get("kind") == "CHAOS_INJECTED", (
        f"kill not attributed to injection: {pre}")
    counts = report["counts"]
    assert counts.get("TASK_RETRY", 0) + counts.get(
        "RECONSTRUCT_DONE", 0) > 0, f"no recovery activity: {counts}"
    print("CHAOS-OK")
finally:
    cluster.shutdown()
"""


def step_chaos() -> Tuple[str, str]:
    """8-virtual-node seeded kill drill: tasks survive, one attributed
    incident in the recovery report."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    with tempfile.NamedTemporaryFile(
            "w", suffix="_rtpu_chaos.py", delete=False) as f:
        f.write(_CHAOS_SRC)
        path = f.name
    try:
        proc = subprocess.run(
            [sys.executable, path], env=env,
            capture_output=True, text=True, timeout=180)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    out = (proc.stdout or "") + (proc.stderr or "")
    if proc.returncode == 0 and "CHAOS-OK" in proc.stdout:
        return "ok", ("8 vnodes, seeded kill mid-fanout: 64/64 tasks, "
                      "1 attributed incident")
    return "FAIL", out[-4000:]


_STEPS: List[Tuple[str, Callable[[], Tuple[str, str]]]] = [
    ("lint", step_lint),
    ("events", step_events),
    ("pipeline", step_pipeline),
    ("podracer", step_podracer),
    ("recorder", step_recorder),
    ("profile", step_profile),
    ("refsan", step_refsan),
    ("collsan", step_collsan),
    ("chaos", step_chaos),
    ("locktrace", step_locktrace),
    ("threadguard", step_threadguard),
    ("stress", step_stress),
]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.check",
        description="one-shot lint + runtime-instrumentation + "
                    "sanitizer gate")
    parser.add_argument("--only", choices=[n for n, _ in _STEPS],
                        help="run a single step")
    args = parser.parse_args(argv)

    failed = False
    for name, fn in _STEPS:
        if args.only and name != args.only:
            continue
        t0 = time.monotonic()
        try:
            status, detail = fn()
        except Exception as exc:
            status, detail = "FAIL", f"step crashed: {exc!r}"
        dt = time.monotonic() - t0
        line = f"check: {name:<12} {status}  ({dt:.1f}s)"
        if status == "SKIP" and detail:
            line += f"  [{detail}]"
        print(line)
        if detail and status == "FAIL":
            print(detail)
            failed = True
        elif status == "ok" and detail:
            print(f"       {detail}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
