"""Cluster-wide sampling profiler (reference: py-spy-backed stack
sampling behind the reference dashboard's per-worker flamegraphs).

A per-process daemon thread samples every live thread's stack via
``sys._current_frames()`` at ``profiler_hz`` (default 101 — a prime, so
the sampler doesn't phase-lock with 10ms/100ms periodic work) and folds
each stack into a collapsed-stack count keyed by thread *role*
(io-loop / executor / main / flight-flush / …). Workers ship their
cumulative counts to the driver over the same control channel the
flight recorder uses (``profile_push``); the driver store keeps the
latest snapshot per process, so pushes are idempotent and a lost one
costs staleness, not correctness.

Exports: ``ray_tpu.profile_dump()`` (folded text — every flamegraph
tool eats it), ``util/timeline.speedscope_profile()`` (speedscope JSON),
``GET /api/profile`` + the dashboard's #/profiler flamegraph view.

Gating (PERF.md discipline): opt-in via ``RAY_TPU_PROFILER=1`` (env,
not config — it must ride the inherited environment into spawned
workers, like refsan). When off, nothing runs — no thread, no
per-sample cost; the only residue is the module-level ``PROFILER is
None`` gate on the read paths.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_HZ = 101
MAX_STACK_DEPTH = 64
# Distinct collapsed stacks kept per process before folding new ones
# into an <overflow> bucket — bounds sampler memory on pathological
# (deep-recursion / codegen) workloads.
MAX_UNIQUE_STACKS = 20_000

_ENV_FLAG = "RAY_TPU_PROFILER"


def _env_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "yes", "on")


def _role(thread_name: str) -> str:
    """Fold raw thread names into the stable role keys the folded
    output and the dashboard group by."""
    name = thread_name or ""
    if name.startswith("rtpu-io-loop"):
        return "io-loop"
    if (name.startswith("task-runner") or name.startswith("actor-loop")
            or name.startswith("ThreadPoolExecutor")):
        return "executor"
    if name == "MainThread":
        return "main"
    if name == "flight-flush":
        return "flight-flush"
    return name or "other"


class Sampler(threading.Thread):
    """Per-process sampling daemon. ``counts`` maps a collapsed stack
    (``role;frame;frame;…`` root-first) to how many samples landed in
    it; reads are racy-but-safe (dict ops are atomic under the GIL and
    a torn read only miscounts the snapshot by one sample)."""

    def __init__(self, label: str, hz: int = DEFAULT_HZ):
        super().__init__(name="rtpu-profiler", daemon=True)
        self.label = label
        self.hz = max(1, int(hz))
        self.counts: Dict[str, int] = {}
        self.samples = 0
        self.started_at = time.time()
        self._stop_ev = threading.Event()

    def run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop_ev.wait(interval):
            try:
                self.sample_once()
            except Exception:  # graftlint: disable=GL004
                pass  # a torn frame walk must never kill the sampler

    def stop(self) -> None:
        self._stop_ev.set()

    def sample_once(self) -> None:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        counts = self.counts
        for tid, frame in frames.items():
            if tid == me:
                continue  # never profile the profiler
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < MAX_STACK_DEPTH:
                code = f.f_code
                stack.append("%s:%s" % (
                    os.path.basename(code.co_filename), code.co_name))
                f = f.f_back
            stack.reverse()  # folded convention: root first
            key = _role(names.get(tid, "")) + ";" + ";".join(stack)
            if key not in counts and len(counts) >= MAX_UNIQUE_STACKS:
                key = _role(names.get(tid, "")) + ";<overflow>"
            counts[key] = counts.get(key, 0) + 1
            self.samples += 1

    def snapshot(self) -> Dict[str, Any]:
        return {"counts": dict(self.counts), "samples": self.samples,
                "hz": self.hz, "started_at": self.started_at}


# Module gate — read paths check ``PROFILER is not None``.
PROFILER: Optional[Sampler] = None


def enabled() -> bool:
    return PROFILER is not None


def enable(label: Optional[str] = None, hz: Optional[int] = None) -> Sampler:
    """Start (or restart) the in-process sampler."""
    global PROFILER
    disable()
    if hz is None:
        from ray_tpu.core.config import get_config
        hz = get_config().profiler_hz
    sampler = Sampler(label or f"proc:{os.getpid()}", hz=hz)
    sampler.start()
    PROFILER = sampler
    return sampler


def disable() -> Optional[Sampler]:
    """Stop the sampler; returns it (counts intact) for late reads."""
    global PROFILER
    sampler = PROFILER
    PROFILER = None
    if sampler is not None:
        sampler.stop()
    return sampler


# --- driver-side store ---------------------------------------------------

class ProfileStore:
    """Latest profile snapshot per process label. Replace-on-push:
    workers send cumulative counts, so the newest push is the whole
    truth for that process and dedup/ordering logic is unnecessary."""

    def __init__(self):
        self._lock = threading.Lock()
        self._procs: Dict[str, Dict[str, Any]] = {}

    def push(self, label: str, counts: Dict[str, int], samples: int,
             hz: int) -> None:
        with self._lock:
            self._procs[label] = {
                "counts": dict(counts), "samples": int(samples),
                "hz": int(hz), "updated_at": time.time(),
            }

    def profiles(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {label: dict(snap)
                    for label, snap in self._procs.items()}


_STORE: Optional[ProfileStore] = None


def get_store() -> ProfileStore:
    global _STORE
    if _STORE is None:
        _STORE = ProfileStore()
    return _STORE


def store_push(label: str, counts: Dict[str, int], samples: int,
               hz: int) -> None:
    get_store().push(label, counts, samples, hz)


def merged_profiles() -> Dict[str, Dict[str, Any]]:
    """label -> {counts, samples, hz}: pushed worker snapshots plus the
    live local sampler (driver samples never cross a channel)."""
    out = get_store().profiles()
    sampler = PROFILER
    if sampler is not None:
        out[sampler.label] = sampler.snapshot()
    return out


# --- process wiring ------------------------------------------------------

def init_driver() -> None:
    """Reset the store and start the driver's sampler when the env flag
    is set. Called from DriverRuntime.__init__ (the env flag itself is
    what spawned workers inherit — nothing to mirror)."""
    global _STORE
    _STORE = ProfileStore()
    disable()
    stop_pusher()
    if _env_enabled():
        enable(label=f"driver:{os.getpid()}")


def init_worker(rt, worker_id) -> None:
    """Start the sampler + the push thread in a worker process (no-op
    unless the driver ran with RAY_TPU_PROFILER=1)."""
    if not _env_enabled():
        return
    from ray_tpu.core.config import get_config
    sampler = enable(label=f"worker:{worker_id.hex()[:12]}:pid:{os.getpid()}")
    start_pusher(rt, sampler,
                 interval_s=get_config().profiler_push_interval_s)


class _Pusher(threading.Thread):
    """Worker-side daemon shipping cumulative counts to the driver
    store every interval (flight-recorder _Flusher discipline: backoff
    on failure, give up after 3 consecutive — the channel is gone)."""

    def __init__(self, rt, sampler: Sampler, interval_s: float):
        super().__init__(name="profile-push", daemon=True)
        self._rt = rt
        self._sampler = sampler
        self._interval = max(0.1, float(interval_s))
        self._stop = threading.Event()

    def push_once(self) -> None:
        snap = self._sampler.snapshot()
        self._rt.gcs_call("profile_push", self._sampler.label,
                          snap["counts"], snap["samples"], snap["hz"])

    def run(self) -> None:
        from ray_tpu.util.backoff import Backoff

        backoff = Backoff(initial_s=self._interval,
                          max_s=8 * self._interval)
        failures = 0
        delay = self._interval
        while not self._stop.wait(delay):
            try:
                self.push_once()
                failures = 0
                backoff.reset()
                delay = self._interval
            except Exception:  # noqa: BLE001
                failures += 1
                if failures >= 3:
                    return
                delay = backoff.next_delay()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.push_once()  # final snapshot, best effort
        except Exception:  # graftlint: disable=GL004
            pass  # shutdown race: the control channel may be gone


_pusher: Optional[_Pusher] = None


def start_pusher(rt, sampler: Sampler, interval_s: float) -> None:
    global _pusher
    _pusher = _Pusher(rt, sampler, interval_s)
    _pusher.start()


def stop_pusher() -> None:
    global _pusher
    if _pusher is not None:
        _pusher.stop()
        _pusher = None


# --- export --------------------------------------------------------------

def folded(proc: Optional[str] = None) -> Dict[str, int]:
    """Merged collapsed-stack counts (``proc;role;frame;… -> n``),
    optionally narrowed to one process label."""
    out: Dict[str, int] = {}
    for label, snap in merged_profiles().items():
        if proc is not None and label != proc:
            continue
        for stack, n in snap.get("counts", {}).items():
            key = f"{label};{stack}"
            out[key] = out.get(key, 0) + int(n)
    return out


def dump(filename: Optional[str] = None,
         proc: Optional[str] = None) -> str:
    """Folded text: one ``proc;role;frame;frame count`` line per stack
    — feed it to any flamegraph/speedscope importer."""
    lines = [f"{stack} {n}"
             for stack, n in sorted(folded(proc).items())]
    text = "\n".join(lines) + ("\n" if lines else "")
    if filename:
        with open(filename, "w") as f:
            f.write(text)
    return text


def capture(filename: Optional[str] = None) -> Dict[str, Any]:
    """JSON capture for ``profdiff``: per-process cumulative counts."""
    payload = {
        "kind": "rtpu-profile",
        "procs": {label: {"counts": snap.get("counts", {}),
                          "samples": snap.get("samples", 0),
                          "hz": snap.get("hz", 0)}
                  for label, snap in merged_profiles().items()},
    }
    if filename:
        import json
        with open(filename, "w") as f:
            json.dump(payload, f, indent=1)
    return payload
