"""refsan: opt-in distributed object-lifetime sanitizer.

The static half of the ownership contract lives in graftlint's
GL014-GL017 (``ray_tpu/devtools/lint/rules/ownership.py``); this module
is the runtime half, in the locktrace/threadguard mold: a cluster-wide
reference *ledger* hooked into every lifetime transition of the object
plane —

* client/worker REF_ADD / REF_DROP sends (``core/worker.py``,
  ``core/client.py``),
* head-side ``ReferenceCounter`` add / drop / grace-reclaim
  (``core/task_manager.py`` + the deleter in ``core/runtime.py``),
* ``_pin_contained`` containment pins,
* ``unpack_pinned`` zero-copy view creation / finalize
  (``core/serialization.py``),
* shm arena slot alloc / pin / release / delete
  (``core/object_store.py``).

Each event is stamped ``(seq, oid, holder, kind, stack_hash, extra)``.
Worker ledgers are flushed to the driver over the same control channel
the flight recorder uses (``gcs_call("refsan_push", ...)``); the driver
folds a per-object state machine over the merged stream and reports:

* **leaked pins** — a store pin still open at shutdown with no live
  zero-copy view backing it (evaluated for the driver's own ledger
  only: a killed worker's truncated journal must not fabricate leaks),
* **double-release** — a slot release with no pin outstanding,
* **negative counts** — a reference drop on a count that is already
  gone,
* **grace violations** — a borrow registration landing *after* the
  owner already reclaimed the object (the PR-13 Sebulba class:
  release-before-grace with an in-flight borrow),
* **use-after-release** — a live ``unpack_pinned`` view reading a
  poisoned arena range (the PR-11 class), made deterministic by the
  *eviction canary* below instead of waiting for a flaky aliased read.

**Eviction canary** (``RAY_TPU_REFSAN_CANARY=1``): when a store slot is
deleted while its refsan shadow pin count is zero, the payload range is
poisoned with ``0xDB`` bytes first, and every registered live view is
verified against the poison — a view created by a buggy early-release
path (pin dropped while the value is alive) reads the canary the
moment the slot is freed, not whenever the arena happens to reuse it.

**Hostile eviction** (``system_config={"refsan_hostile_eviction": 1}``
or ``RTPU_REFSAN_HOSTILE_EVICTION=1``): shrinks the owner's borrow
grace window to ~0 so deferred reclaims fire at the earliest legal
moment — tier-1 uses it to force the PR-13-shaped races
deterministically.

Enable with::

    RAY_TPU_REFSAN=1 python my_driver.py
    RAY_TPU_REFSAN=1 RAY_TPU_REFSAN_CANARY=1 pytest ...

With ``RAY_TPU_REFSAN`` unset every hook is two loads and a compare::

    led = refsan.LEDGER
    if led is not None:
        led.record(...)

Like everything in devtools, importing this module must stay cheap:
no jax, no runtime imports.
"""

from __future__ import annotations

import itertools
import logging
import os
import sys
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_ENV_FLAG = "RAY_TPU_REFSAN"
_CANARY_ENV = "RAY_TPU_REFSAN_CANARY"

#: single poison byte; a 16-byte run of it marks a freed arena range.
POISON_BYTE = 0xDB
_POISON_PROBE = bytes([POISON_BYTE]) * 16

# event kinds folded into findings (the rest are narrative)
KIND_REF_ADD = "ref_add"
KIND_REF_DROP = "ref_drop"
KIND_REF_DROP_MISSING = "ref_drop_missing"
KIND_REF_ZERO = "ref_zero"
KIND_REF_DEFER = "ref_defer"
KIND_RECLAIM_SKIP = "reclaim_skip"
KIND_DELETED = "deleted"
KIND_PIN_CONTAINED = "pin_contained"
KIND_BORROW_SEND = "borrow_send"
KIND_SLOT_ALLOC = "slot_alloc"
KIND_SLOT_PIN = "slot_pin"
KIND_SLOT_RELEASE = "slot_release"
KIND_SLOT_DELETE = "slot_delete"
KIND_VIEW_CREATE = "view_create"
KIND_CANARY_HIT = "canary_hit"


def enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "yes", "on")


def canary_enabled() -> bool:
    return os.environ.get(_CANARY_ENV, "").strip().lower() in (
        "1", "true", "yes", "on")


def _stack_hash(depth: int = 5) -> int:
    """Compact fingerprint of the caller's stack: a hash over the
    (filename, lineno) pairs of the next few frames. Cheap enough for
    an opt-in tool; rich enough to attribute a leak to its call site."""
    frames = []
    try:
        f = sys._getframe(2)
    except ValueError:
        return 0
    while f is not None and len(frames) < depth:
        frames.append((f.f_code.co_filename, f.f_lineno))
        f = f.f_back
    return hash(tuple(frames)) & 0xFFFFFFFF


class _ViewRec:
    """A live zero-copy view registered by ``unpack_pinned``."""

    __slots__ = ("oid", "wref", "size", "stack", "holder")

    def __init__(self, oid: str, holder_obj: Any, size: int, stack: int):
        self.oid = oid
        self.wref = weakref.ref(holder_obj)
        self.size = size
        self.stack = stack


_view_ctx = threading.local()


class view_context:
    """Context manager naming the object whose buffers ``unpack_pinned``
    is about to hand out, so view registration can attribute them."""

    def __init__(self, oid_hex: str):
        self._oid = oid_hex

    def __enter__(self):
        self._prev = getattr(_view_ctx, "oid", None)
        _view_ctx.oid = self._oid
        return self

    def __exit__(self, *exc):
        _view_ctx.oid = self._prev
        return False


class Ledger:
    """Per-process reference ledger. ``record`` appends one tuple per
    lifetime transition (list.append is atomic under the GIL); the
    shadow pin table and view registry back the canary checker."""

    def __init__(self, label: str = "", canary: Optional[bool] = None):
        self.label = label or f"pid:{os.getpid()}"
        self.canary = canary_enabled() if canary is None else bool(canary)
        self._events: List[tuple] = []
        self._seq = itertools.count()
        self._lock = threading.Lock()
        # shadow store pins per (store_name, oid_hex): +1 get_buffer,
        # -1 release. Drives the poison-on-delete decision.
        self._pins: Dict[Tuple[str, str], int] = {}
        # last known arena range per (store_name, oid_hex)
        self._ranges: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._views: List[_ViewRec] = []

    # -- event stream ---------------------------------------------------

    def record(self, kind: str, oid_hex: str,
               extra: Optional[dict] = None) -> None:
        # lock-free hot path: list.append is atomic under the GIL and
        # the itertools ticket orders events; readers only slice the
        # append-only list (flight-recorder discipline)
        self._events.append((next(self._seq), oid_hex, self.label, kind,  # graftlint: disable=GL001
                             _stack_hash(), extra))

    def snapshot(self, since: int = 0) -> List[tuple]:
        """Events with index >= ``since`` (the list is append-only)."""
        return self._events[since:]

    def event_count(self) -> int:
        return len(self._events)

    # -- reference-counter hooks (called under the counter's lock) ------

    def ref_event(self, kind: str, oid_bin: bytes, count: int,
                  role: str) -> None:
        self.record(kind, oid_bin.hex(), {"count": count, "role": role})

    # -- store hooks ------------------------------------------------------

    def slot_alloc(self, store: str, oid_bin: bytes, off: int,
                   size: int) -> None:
        oid = oid_bin.hex()
        with self._lock:
            self._ranges[(store, oid)] = (off, size)
        self.record(KIND_SLOT_ALLOC, oid, {"store": store, "size": size})

    def slot_pin(self, store: str, oid_bin: bytes, off: int,
                 size: int) -> None:
        oid = oid_bin.hex()
        with self._lock:
            self._pins[(store, oid)] = self._pins.get((store, oid), 0) + 1
            self._ranges[(store, oid)] = (off, size)
        self.record(KIND_SLOT_PIN, oid, {"store": store})

    def slot_release(self, store: str, oid_bin: bytes) -> None:
        oid = oid_bin.hex()
        with self._lock:
            self._pins[(store, oid)] = self._pins.get((store, oid), 0) - 1
            if self._pins[(store, oid)] <= 0:
                count = self._pins.pop((store, oid))
            else:
                count = self._pins[(store, oid)]
        self.record(KIND_SLOT_RELEASE, oid,
                    {"store": store, "pins": count})

    def on_slot_delete(self, store: str,
                       oid_bin: bytes) -> Optional[Tuple[int, int]]:
        """Record the delete; in canary mode, return the payload range
        to poison when no shadow pin is outstanding (a legitimately
        pinned slot is left untouched — the native store defers its
        free, and poisoning it would corrupt a correct reader)."""
        oid = oid_bin.hex()
        with self._lock:
            pins = self._pins.get((store, oid), 0)
            rng = self._ranges.pop((store, oid), None)
        self.record(KIND_SLOT_DELETE, oid, {"store": store, "pins": pins})
        if self.canary and pins <= 0:
            return rng
        return None

    def pin_count(self, store: str, oid_bin: bytes) -> int:
        with self._lock:
            return self._pins.get((store, oid_bin.hex()), 0)

    # -- view registry / canary ------------------------------------------

    def register_view(self, holder_obj: Any, size: int) -> None:
        """Register a buffer-holder handed out by ``unpack_pinned``.
        The weakref tracks the VALUE's lifetime (arrays keep their
        holder alive through ``.base`` chains), independent of whether
        ``on_release`` was wired correctly — which is the point."""
        oid = getattr(_view_ctx, "oid", None)
        if oid is None:
            return
        try:
            rec = _ViewRec(oid, holder_obj, size, _stack_hash())
        except TypeError:
            return  # holder type not weakref-able; nothing to track
        with self._lock:
            self._views.append(rec)
        self.record(KIND_VIEW_CREATE, oid, {"size": size})

    def live_views(self) -> Dict[str, int]:
        """oid_hex -> number of live registered views (dead weakrefs
        are compacted as a side effect)."""
        out: Dict[str, int] = {}
        with self._lock:
            alive = [r for r in self._views if r.wref() is not None]
            self._views = alive
        for rec in alive:
            out[rec.oid] = out.get(rec.oid, 0) + 1
        return out

    def verify_views(self) -> int:
        """Check every live view against the poison pattern; a hit
        means its arena range was freed under it (use-after-release).
        Each hit is recorded once. Returns the number of new hits."""
        with self._lock:
            views = list(self._views)
        hits = 0
        dead: List[_ViewRec] = []
        for rec in views:
            holder = rec.wref()
            if holder is None:
                continue
            try:
                probe = bytes(memoryview(holder)[:len(_POISON_PROBE)])
            except (ValueError, TypeError, SystemError):
                continue  # buffer no longer exportable; nothing to read
            if probe == _POISON_PROBE:
                self.record(KIND_CANARY_HIT, rec.oid,
                            {"view_stack": rec.stack, "size": rec.size})
                dead.append(rec)
                hits += 1
        if dead:
            with self._lock:
                self._views = [r for r in self._views if r not in dead]
        return hits


# The module-level gate. Hot paths read this once and None-check it;
# rebinding is atomic under the GIL so enable/disable race nothing.
LEDGER: Optional[Ledger] = None


def enable(label: str = "", canary: Optional[bool] = None) -> Ledger:
    global LEDGER
    LEDGER = Ledger(label=label, canary=canary)
    return LEDGER


def disable() -> None:
    global LEDGER
    LEDGER = None


# --- driver-side collector ----------------------------------------------

class _RefsanStore:
    """Driver-held worker ledgers pushed over ``refsan_push``."""

    def __init__(self):
        self.lock = threading.Lock()
        self._procs: Dict[str, List[tuple]] = {}

    def push(self, label: str, events: List[tuple]) -> None:
        # Brief and lock-only: runs in the GCS dispatch path, which may
        # be the head's IO-loop thread.
        with self.lock:
            bucket = self._procs.setdefault(label, [])
            last = bucket[-1][0] if bucket else -1
            for ev in events:
                if ev[0] > last:
                    bucket.append(tuple(ev))
                    last = ev[0]

    def journals(self) -> Dict[str, List[tuple]]:
        with self.lock:
            return {label: list(evs)
                    for label, evs in sorted(self._procs.items())}


_STORE: Optional[_RefsanStore] = None
_final_findings: Optional[List[dict]] = None


def get_store() -> _RefsanStore:
    global _STORE
    if _STORE is None:
        _STORE = _RefsanStore()
    return _STORE


def store_push(label: str, events: List[tuple]) -> None:
    get_store().push(label, events)


def merged_events() -> List[tuple]:
    """Every collected worker event plus the local ledger's, in a
    per-holder seq-consistent order."""
    out: List[tuple] = []
    store = _STORE
    if store is not None:
        for events in store.journals().values():
            out.extend(events)
    led = LEDGER
    if led is not None:
        out.extend(led.snapshot())
    return out


# --- process wiring ------------------------------------------------------

def init_driver() -> None:
    """Reset collector state and (when ``RAY_TPU_REFSAN`` is set)
    enable the driver's ledger. Called from ``Runtime.__init__``; the
    env flag itself rides into forked workers untouched."""
    global _STORE, _final_findings
    _STORE = _RefsanStore()
    _final_findings = None
    stop_flusher()
    if enabled():
        enable(label=f"driver:{os.getpid()}")
    else:
        disable()


def init_worker(rt, worker_id) -> None:
    """Enable the ledger and start the push flusher in a worker process
    (no-op unless the driver session runs with ``RAY_TPU_REFSAN``)."""
    if not enabled():
        return
    led = enable(label=f"worker:{worker_id.hex()[:12]}:pid:{os.getpid()}")
    start_flusher(rt, led)


class _Flusher(threading.Thread):
    """Worker-side daemon: periodically push the ledger increment to
    the driver over the control channel (same route as flight_push;
    replies are delivered by the worker's main recv loop)."""

    def __init__(self, rt, ledger: Ledger, interval_s: float = 0.25):
        super().__init__(name="refsan-flush", daemon=True)
        self._rt = rt
        self._ledger = ledger
        self._interval = max(0.02, float(interval_s))
        self._sent = 0
        self._stop = threading.Event()

    def flush_once(self) -> None:
        events = self._ledger.snapshot(since=self._sent)
        if not events:
            return
        self._rt.gcs_call("refsan_push", self._ledger.label, events)
        self._sent += len(events)

    def run(self) -> None:
        from ray_tpu.util.backoff import Backoff

        # Failed pushes back off with jitter (util/backoff.py) instead
        # of re-hammering a struggling control channel every interval.
        backoff = Backoff(initial_s=self._interval,
                          max_s=8 * self._interval)
        failures = 0
        delay = self._interval
        while not self._stop.wait(delay):
            try:
                self.flush_once()
                failures = 0
                backoff.reset()
                delay = self._interval
            except Exception:  # noqa: BLE001 — channel gone at shutdown
                failures += 1
                if failures >= 3:
                    return
                delay = backoff.next_delay()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.flush_once()  # final increment, best effort
        except Exception:  # graftlint: disable=GL004
            pass  # shutdown race: the control channel may be gone


_flusher: Optional[_Flusher] = None


def start_flusher(rt, ledger: Ledger) -> None:
    global _flusher
    _flusher = _Flusher(rt, ledger)
    _flusher.start()


def stop_flusher() -> None:
    global _flusher
    if _flusher is not None:
        _flusher.stop()
        _flusher = None


# --- the fold -------------------------------------------------------------

def fold(events: List[tuple],
         live_views: Optional[Dict[str, int]] = None,
         local_label: Optional[str] = None) -> List[dict]:
    """Fold the merged event stream into findings. Each finding is a
    dict: ``{"kind", "oid", "holder", "detail"}``.

    ``live_views`` (oid -> live view count, from the local ledger) and
    ``local_label`` scope the leak check to the process we can actually
    observe — a worker killed mid-test truncates its journal, and a
    truncated journal must not read as a leak."""
    findings: List[dict] = []
    # per-holder event streams stay seq-ordered; sort per holder
    by_holder: Dict[str, List[tuple]] = {}
    for ev in events:
        by_holder.setdefault(ev[2], []).append(ev)
    for holder, evs in by_holder.items():
        evs.sort(key=lambda e: e[0])
        deleted_at: Dict[str, int] = {}
        pins: Dict[Tuple[str, str], int] = {}
        added: set = set()
        for seq, oid, _h, kind, _stack, extra in evs:
            if kind == KIND_REF_ADD:
                added.add(oid)
            if kind == KIND_REF_DROP_MISSING:
                # only a double-drop: a drop on an oid this holder never
                # registered is a cross-epoch artifact (an ObjectRef
                # surviving a runtime restart __del__s into the fresh
                # counter), not a count gone negative
                if oid in added:
                    findings.append({
                        "kind": "negative_count", "oid": oid,
                        "holder": holder,
                        "detail": "reference dropped below zero (second "
                                  "drop on a count already at zero)"})
            elif kind == KIND_DELETED:
                deleted_at[oid] = seq
            elif kind == KIND_REF_ADD:
                role = (extra or {}).get("role")
                if role == "owner" and oid in deleted_at:
                    findings.append({
                        "kind": "grace_violation", "oid": oid,
                        "holder": holder,
                        "detail": "borrow registered after the owner "
                                  "reclaimed the object (release-before-"
                                  "grace with an in-flight borrow)"})
                    del deleted_at[oid]  # report once per reclaim
            elif kind == KIND_SLOT_PIN:
                store = (extra or {}).get("store", "")
                pins[(store, oid)] = pins.get((store, oid), 0) + 1
            elif kind == KIND_SLOT_RELEASE:
                store = (extra or {}).get("store", "")
                n = pins.get((store, oid), 0)
                if n <= 0:
                    findings.append({
                        "kind": "double_release", "oid": oid,
                        "holder": holder,
                        "detail": f"store pin released with none "
                                  f"outstanding (store={store})"})
                else:
                    pins[(store, oid)] = n - 1
            elif kind == KIND_CANARY_HIT:
                findings.append({
                    "kind": "use_after_release", "oid": oid,
                    "holder": holder,
                    "detail": "live zero-copy view read the eviction "
                              "canary: its arena range was freed while "
                              "the deserialized value was still alive"})
        # leaked pins: only judged for the local (driver) holder, whose
        # live-view registry we can consult.
        if local_label is not None and holder == local_label:
            views = dict(live_views or {})
            for (store, oid), n in pins.items():
                if n <= 0:
                    continue
                backing = views.get(oid, 0)
                if n > backing:
                    findings.append({
                        "kind": "leaked_pin", "oid": oid,
                        "holder": holder,
                        "detail": f"{n} store pin(s) still open with "
                                  f"{backing} live view(s) backing them "
                                  f"(store={store})"})
    return findings


def report() -> List[dict]:
    """Fold the merged journals into findings (plus anything a
    shutdown-time fold already caught). Empty when refsan is off."""
    led = LEDGER
    if led is None and _STORE is None:
        return list(_final_findings or [])
    if led is not None:
        led.verify_views()
    findings = fold(
        merged_events(),
        live_views=led.live_views() if led is not None else None,
        local_label=led.label if led is not None else None)
    if _final_findings:
        seen = {(f["kind"], f["oid"], f["holder"]) for f in findings}
        findings.extend(f for f in _final_findings
                        if (f["kind"], f["oid"], f["holder"]) not in seen)
    return findings


def on_shutdown() -> None:
    """Runtime shutdown hook: fold once while worker journals and the
    store state are still current, and keep the result for late
    ``report()`` calls (the ledger itself is torn down with the
    session)."""
    global _final_findings
    if LEDGER is None:
        return
    findings = report()
    _final_findings = findings
    for f in findings:
        logger.warning("refsan: %s oid=%s holder=%s: %s",
                       f["kind"], f["oid"][:12], f["holder"], f["detail"])


def format_findings(findings: List[dict]) -> str:
    return "\n".join(
        f"refsan: {f['kind']} oid={f['oid'][:12]} holder={f['holder']}: "
        f"{f['detail']}" for f in findings)
