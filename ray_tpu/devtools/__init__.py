"""Developer tooling: framework-aware static analysis (graftlint,
including the interprocedural GL009-GL012 loop-safety rules), runtime
concurrency diagnostics (locktrace lock-order tracing, threadguard
loop-affinity assertions + stall watchdog), and the one-shot
``python -m ray_tpu.devtools.check`` gate.

Nothing in this package imports jax or the runtime — it must stay cheap
to import from CI guards and from production modules that only want a
lock factory (``locktrace.traced_lock``) or an affinity decorator
(``threadguard.loop_only``).
"""
