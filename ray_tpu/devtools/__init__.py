"""Developer tooling: framework-aware static analysis (graftlint) and
runtime concurrency diagnostics (locktrace).

Nothing in this package imports jax or the runtime — it must stay cheap
to import from CI guards and from production modules that only want a
lock factory (``locktrace.traced_lock``).
"""
