"""threadguard: opt-in runtime thread-affinity enforcement for the
IO-loop core.

The static half of the contract lives in graftlint's GL009-GL012
(``ray_tpu/devtools/lint/rules/threadguard.py``); this module is the
runtime half, in the locktrace mold:

* ``@loop_only`` — asserts at call time that the method runs on its
  owning IO loop's thread, with a diagnostic naming the expected and
  actual threads. With ``RAY_TPU_THREADGUARD`` unset the decorator
  returns the function *unchanged* — zero overhead, plain functions.
* ``@loop_owned("attr", ...)`` — class decorator declaring which
  attributes are loop-thread-only. Purely declarative: it feeds the
  static GL011 rule and documentation; no runtime wrapping.
* ``LoopStallWatchdog`` — samples the loop thread's stack via
  ``sys._current_frames`` whenever a dispatch exceeds
  ``RAY_TPU_THREADGUARD_STALL_S`` (default 1.0s), reporting the
  blocking frame so GL009 escapes get caught live. Wired up by
  ``IOLoop`` itself when threadguard is enabled; it only logs and
  records, never raises.

Enable with::

    RAY_TPU_THREADGUARD=1 python my_driver.py
    RAY_TPU_THREADGUARD=1 RAY_TPU_THREADGUARD_STALL_S=0.25 pytest ...

Like everything in devtools, importing this module must stay cheap:
no jax, no runtime imports.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

_ENV_FLAG = "RAY_TPU_THREADGUARD"
_STALL_ENV = "RAY_TPU_THREADGUARD_STALL_S"
_STALL_DEFAULT_S = 1.0

_reports: List[dict] = []
_reports_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").strip().lower() in (
        "1", "true", "yes", "on")


def stall_default_s() -> float:
    try:
        return float(os.environ.get(_STALL_ENV, _STALL_DEFAULT_S))
    except ValueError:
        return _STALL_DEFAULT_S


class LoopAffinityError(AssertionError):
    """A @loop_only method was called off its owning loop's thread."""


def _resolve_loop(obj, loop_attr: Optional[str]):
    """Find the owning IOLoop (duck-typed: has on_loop_thread) on
    ``obj``: an explicit dotted ``loop_attr`` path, ``obj`` itself,
    or a conventional attribute (_loop/_io/loop/io). Returns None when
    unresolvable — the guard then passes through rather than guessing."""
    if loop_attr:
        target = obj
        for part in loop_attr.split("."):
            target = getattr(target, part, None)
            if target is None:
                return None
        if callable(getattr(target, "on_loop_thread", None)):
            return target
        return None
    if callable(getattr(obj, "on_loop_thread", None)):
        return obj
    for name in ("_loop", "_io", "loop", "io"):
        cand = getattr(obj, name, None)
        if cand is not None and \
                callable(getattr(cand, "on_loop_thread", None)):
            return cand
    return None


def loop_only(fn: Optional[Callable] = None, *,
              loop_attr: Optional[str] = None):
    """Mark a method as loop-thread-only.

    Always sets ``_tg_loop_only`` (consumed by the static GL009-GL011
    seeding); when ``RAY_TPU_THREADGUARD`` is enabled at decoration
    time, also wraps the method to raise ``LoopAffinityError`` when
    called from any other thread. ``loop_attr`` is a dotted attribute
    path to the owning loop for classes that don't follow the
    _loop/_io convention (e.g. ``loop_attr="conn._loop"``)."""

    def deco(f: Callable) -> Callable:
        f._tg_loop_only = True
        if not enabled():
            return f

        @functools.wraps(f)
        def wrapper(self, *args, **kwargs):
            loop = _resolve_loop(self, loop_attr)
            if loop is not None and not loop.on_loop_thread():
                expected = getattr(loop, "_thread", None)
                raise LoopAffinityError(
                    f"threadguard: {type(self).__name__}."
                    f"{f.__name__}() is @loop_only but was called on "
                    f"thread {threading.current_thread().name!r} "
                    f"(ident={threading.get_ident()}); owning loop "
                    f"thread is "
                    f"{getattr(expected, 'name', '<unknown>')!r} "
                    f"(ident={getattr(expected, 'ident', '?')}). "
                    "Route the call through call_soon/call_later.")
            return f(self, *args, **kwargs)

        wrapper._tg_loop_only = True
        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def loop_owned(*names: str):
    """Class decorator declaring loop-thread-only attributes. Static
    marker for graftlint GL011 (and for readers); merges with any
    declaration on base classes. No runtime wrapping — enforcement of
    attribute affinity is static-only."""

    def deco(cls):
        inherited = set()
        for base in cls.__mro__[1:]:
            inherited |= set(getattr(base, "_tg_loop_owned", ()))
        cls._tg_loop_owned = frozenset(inherited | set(names))
        return cls

    return deco


class LoopStallWatchdog:
    """Samples a loop thread's stack when one dispatch runs too long.

    The loop publishes busy-ness via ``enter()``/``exit_busy()`` around
    each batch of work (callbacks, handlers, timers). A daemon watcher
    thread polls at stall_s/4; when the busy window exceeds
    ``stall_s`` it formats the loop thread's current stack from
    ``sys._current_frames`` and appends a report (one per stall
    episode). It never raises into the loop."""

    def __init__(self, thread: threading.Thread,
                 stall_s: Optional[float] = None):
        self._thread = thread
        self._stall_s = stall_s if stall_s is not None \
            else stall_default_s()
        self._busy_since: Optional[float] = None
        self._reported_for: Optional[float] = None
        self._stop_evt = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch, name="rtpu-threadguard-watchdog",
            daemon=True)
        self._watcher.start()

    # called from the loop thread only
    def enter(self) -> None:
        self._busy_since = time.monotonic()

    def exit_busy(self) -> None:
        self._busy_since = None

    def stop(self) -> None:
        self._stop_evt.set()

    def _watch(self) -> None:
        interval = max(0.01, self._stall_s / 4.0)
        while not self._stop_evt.wait(interval):
            if self._thread.ident is None:
                continue    # loop thread not started yet
            if not self._thread.is_alive():
                return
            t0 = self._busy_since
            if t0 is None or t0 == self._reported_for:
                continue
            stalled = time.monotonic() - t0
            if stalled < self._stall_s:
                continue
            frame = sys._current_frames().get(self._thread.ident)
            stack = "".join(traceback.format_stack(frame)) if frame \
                else "<no frame available>"
            report = {
                "thread": self._thread.name,
                "ident": self._thread.ident,
                "stalled_s": stalled,
                "stack": stack,
            }
            with _reports_lock:
                _reports.append(report)
            logger.warning(
                "threadguard: IO loop thread %r busy for %.3fs "
                "(> %.3fs stall threshold); current stack:\n%s",
                self._thread.name, stalled, self._stall_s, stack)
            # one report per stall episode, keyed by its start stamp
            self._reported_for = t0


def stall_reports() -> List[dict]:
    """Snapshot of watchdog stall reports recorded so far."""
    with _reports_lock:
        return list(_reports)


def reset() -> None:
    """Clear recorded stall reports (test helper)."""
    with _reports_lock:
        del _reports[:]
