"""Loop-thread affinity rules (GL009-GL013).

These are *project* rules: they consume the interprocedural
``ProjectContext`` (callgraph.py) instead of a single file, because
"can this function run on the rtpu-io-loop thread?" is a whole-program
property. The runtime half of the contract lives in
``ray_tpu/devtools/threadguard.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ray_tpu.devtools.lint.annotate import (_MUTATORS, _dotted,
                                            _is_self_attr)
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import ProjectContext, _leaf, \
    body_nodes

_SOCKET_LEAVES = {"recv", "recv_into", "recvfrom", "accept", "connect",
                  "sendall", "create_connection"}
_WAIT_LEAVES = {"wait", "wait_for", "join"}
_RPC_LEAVES = {"gcs_call", "wait_for_nodes", "urlopen"}


def _blocking_reason(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    leaf = _leaf(dotted)
    if dotted == "sleep" or dotted.endswith(".sleep"):
        return f"blocking call {dotted}()"
    if dotted.startswith("subprocess.") or leaf == "Popen":
        return f"subprocess call {dotted}()"
    if leaf in _SOCKET_LEAVES:
        return f"socket operation {dotted}()"
    if leaf in _RPC_LEAVES:
        return f"synchronous control-plane call {dotted}()"
    if leaf in _WAIT_LEAVES:
        if leaf == "join" and "path" in dotted:
            return None     # os.path.join and friends
        recv = call.func.value if isinstance(call.func, ast.Attribute) \
            else None
        if isinstance(recv, ast.Constant):
            return None     # "sep".join(...)
        return f"blocking wait {dotted}()"
    if leaf == "acquire":
        nonblocking = any(
            kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
            and not kw.value.value for kw in call.keywords)
        if call.args and isinstance(call.args[0], ast.Constant) \
                and not call.args[0].value:
            nonblocking = True
        if not nonblocking:
            return f"blocking lock acquire {dotted}()"
    return None


@register
class LoopThreadBlockingCall(Rule):
    id = "GL009"
    name = "loop-thread-blocking-call"
    project = True
    rationale = ("a blocking primitive (sleep/socket/subprocess/"
                 "Event.wait/lock.acquire/sync gcs_call) is reachable "
                 "from an IO-loop callback — the single loop thread "
                 "must never block")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for key in sorted(project.loop_ctx):
            info = project.functions[key]
            for call in project.body_calls(info.node):
                reason = _blocking_reason(call)
                if reason is not None:
                    yield info.ctx.finding(
                        self.id, call,
                        f"{reason} on a loop-thread path "
                        f"({project.chain_str(key)}); defer it with "
                        "call_soon/call_later or move it off-loop")


@register
class LoopThreadMetricRPC(Rule):
    id = "GL010"
    name = "loop-thread-metric-rpc"
    project = True
    rationale = ("Counter.inc/Gauge.set/Histogram.observe/record_batch "
                 "forward worker->driver over a sync gcs_call; from "
                 "the loop thread that reply can only be dispatched by "
                 "the thread that is waiting for it — use record_local")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for key in sorted(project.loop_ctx):
            info = project.functions[key]
            cls = getattr(info.node, "_gl_class", None)
            for call in project.body_calls(info.node):
                dotted = _dotted(call.func)
                if dotted is None:
                    continue
                leaf = _leaf(dotted)
                if leaf == "record_batch":
                    hit = True
                elif leaf in ("inc", "set", "observe") and \
                        isinstance(call.func, ast.Attribute):
                    base = call.func.value
                    hit = (isinstance(base, ast.Name) and
                           base.id in project.metric_globals)
                    attr = _is_self_attr(base)
                    if attr is not None and cls is not None and \
                            (cls.name, attr) in project.metric_attrs:
                        hit = True
                else:
                    hit = False
                if hit:
                    fix = "record_local()" if leaf == "record_batch" \
                        else f"{leaf}_local()"
                    yield info.ctx.finding(
                        self.id, call,
                        f"metric write {dotted}() can RPC the driver "
                        f"from the loop thread "
                        f"({project.chain_str(key)}); use {fix}")


@register
class LoopThreadTracingRPC(Rule):
    id = "GL013"
    name = "loop-thread-tracing-rpc"
    project = True
    rationale = ("tracing.span()/record_span() ship the finished span "
                 "over a sync gcs_call on workers; from the loop thread "
                 "that reply can only be dispatched by the thread that "
                 "is waiting for it — instrument loop-reachable paths "
                 "with the lock-free flight_recorder.record() journal "
                 "instead")

    #: emitters in ray_tpu.util.tracing that end in a sync control-plane
    #: RPC off-driver (profile() is excluded: it appends to a local list)
    _RPC_EMITTERS = {"span", "record_span"}

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for key in sorted(project.loop_ctx):
            info = project.functions[key]
            path = info.ctx.path
            for call in project.body_calls(info.node):
                dotted = _dotted(call.func)
                if dotted is None:
                    continue
                leaf = _leaf(dotted)
                if leaf not in self._RPC_EMITTERS:
                    continue
                if not self._is_tracing_emitter(project, path, dotted):
                    continue
                yield info.ctx.finding(
                    self.id, call,
                    f"span emission {dotted}() can RPC the driver from "
                    f"the loop thread ({project.chain_str(key)}); "
                    "record into the flight_recorder journal instead "
                    "(lock-free, no RPC)")

    @staticmethod
    def _is_tracing_emitter(project: ProjectContext, path: str,
                            dotted: str) -> bool:
        """True when ``dotted`` resolves (via this file's absolute
        imports) to ray_tpu.util.tracing.span/record_span."""
        imports = project._imports.get(path, {})
        base = dotted.split(".", 1)[0]
        imp = imports.get(base)
        if imp is None:
            return False
        module, orig = imp
        resolved = f"{module}.{orig}" if orig else module
        if "." in dotted:
            resolved = resolved + "." + dotted.split(".", 1)[1]
        return resolved in ("ray_tpu.util.tracing.span",
                            "ray_tpu.util.tracing.record_span")


@register
class OffLoopStateMutation(Rule):
    id = "GL011"
    name = "off-loop-state-mutation"
    project = True
    rationale = ("attributes declared @loop_owned (or _loop-prefixed "
                 "on loop-registered classes) are loop-thread-only by "
                 "contract; mutating them from other threads without "
                 "call_soon/call_later is a data race")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for ctx, cls in project.all_classes:
            owned = project.loop_owned.get(id(cls), set())
            registered = bool(owned)
            members = [info for info in project.functions.values()
                       if getattr(info.node, "_gl_class", None) is cls
                       and info.ctx is ctx]
            if not registered:
                registered = any(m.key in project.loop_ctx
                                 for m in members)
            if not registered:
                continue
            for info in members:
                if info.key in project.loop_ctx:
                    continue
                if info.qualname.endswith(".__init__") or \
                        info.qualname == "__init__":
                    continue
                for node in body_nodes(info.node):
                    attr = self._mutated_attr(node)
                    if attr is None:
                        continue
                    if attr in owned or attr.startswith("_loop"):
                        yield ctx.finding(
                            self.id, node,
                            f"loop-owned attribute self.{attr} mutated "
                            f"in {info.qualname}(), which is not on a "
                            "loop-thread path — route it through "
                            "call_soon/call_later or a @loop_only "
                            "method")

    @staticmethod
    def _mutated_attr(node: ast.AST) -> Optional[str]:
        def direct(target) -> Optional[str]:
            if isinstance(target, ast.Subscript):
                return _is_self_attr(target.value)
            return _is_self_attr(target)

        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            return _is_self_attr(node.func.value)
        if isinstance(node, (ast.Assign, ast.Delete)):
            for target in node.targets:
                attr = direct(target)
                if attr is not None:
                    return attr
            return None
        if isinstance(node, ast.AugAssign):
            return direct(node.target)
        return None


@register
class AsyncLoopCallback(Rule):
    id = "GL012"
    name = "async-loop-callback"
    project = True
    rationale = ("the IO loop calls its callbacks synchronously; an "
                 "`async def` (or awaitable-returning) callback builds "
                 "a coroutine nobody awaits and silently never runs")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        seen = set()
        for path, node, qual, reason in project.async_registrations:
            fp = (path, getattr(node, "lineno", 0), qual)
            if fp in seen:
                continue
            seen.add(fp)
            ctx = project.ctxs[path]
            yield ctx.finding(
                self.id, node,
                f"{reason} — the loop never awaits it, so it silently "
                "never runs")
