"""Lifecycle-event rules (GL018).

The static half of the cluster event plane (``core/events.py``):
lifecycle state on GCS records (actor/node ``.state``) is what the
event stream narrates, so a bare ``record.state = ...`` outside an
event-emitting helper silently advances the lifecycle with no event —
the recovery timeline (``devtools/recovery.py``) then shows a gap
where the transition happened. Mutations must go through (or sit in a
function that also calls) one of the emitting helpers.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ray_tpu.devtools.lint.annotate import _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import _leaf

#: GCS tables whose records carry narrated lifecycle state. Placement
#: groups are deliberately out of scope: their state machine predates
#: the event plane and transitions in the scheduler hot path.
_GCS_TABLES = {"actors", "nodes"}

#: a function that calls any of these is an event-emitting helper (or
#: delegates to one) — its .state writes are narrated
_EMITTERS = {"add_cluster_event", "emit", "update_actor_state",
             "mark_node_dead"}


def _table_attr(node: ast.AST) -> bool:
    """``<anything>.actors`` / ``<anything>.nodes`` attribute access."""
    return isinstance(node, ast.Attribute) and node.attr in _GCS_TABLES


def _record_source(value: ast.AST) -> bool:
    """Expression yielding a record out of a GCS table: subscript
    (``self.actors[aid]``) or ``.get(...)`` call on a table attr."""
    if isinstance(value, ast.Subscript) and _table_attr(value.value):
        return True
    if isinstance(value, ast.Call) and \
            isinstance(value.func, ast.Attribute) and \
            value.func.attr == "get" and _table_attr(value.func.value):
        return True
    return False


@register
class SilentLifecycleMutation(Rule):
    id = "GL018"
    name = "silent-lifecycle-mutation"
    rationale = ("actor/node record .state is the lifecycle the cluster "
                 "event plane narrates: a bare `record.state = ...` "
                 "outside an event-emitting helper advances the "
                 "lifecycle with no ClusterEvent, leaving a hole in "
                 "recovery timelines — route the transition through "
                 "gcs.update_actor_state/mark_node_dead or emit the "
                 "event alongside the write")

    def check(self, ctx) -> Iterator[Finding]:
        for fn in (n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            emits = any(
                isinstance(n, ast.Call) and
                _leaf(_dotted(n.func) or "") in _EMITTERS
                for n in ast.walk(fn))
            if emits:
                continue
            # names bound from a GCS-table record in this function
            tracked: Set[str] = set()
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and \
                        _record_source(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            tracked.add(t.id)
                elif isinstance(n, (ast.For, ast.AsyncFor)) and \
                        isinstance(n.iter, ast.Call) and \
                        isinstance(n.iter.func, ast.Attribute) and \
                        n.iter.func.attr in ("values", "items") and \
                        _table_attr(n.iter.func.value):
                    tgt = n.target
                    if n.iter.func.attr == "items" and \
                            isinstance(tgt, ast.Tuple) and \
                            len(tgt.elts) == 2:
                        tgt = tgt.elts[1]
                    if isinstance(tgt, ast.Name):
                        tracked.add(tgt.id)
            for n in ast.walk(fn):
                if not isinstance(n, ast.Assign):
                    continue
                for t in n.targets:
                    if not (isinstance(t, ast.Attribute) and
                            t.attr == "state"):
                        continue
                    direct = _record_source(t.value)
                    via_name = (isinstance(t.value, ast.Name) and
                                t.value.id in tracked)
                    if direct or via_name:
                        yield ctx.finding(
                            self.id, n,
                            "lifecycle .state mutated on a GCS record "
                            f"in {fn.name}() with no event emitted — "
                            "the transition is invisible to recovery "
                            "timelines; go through update_actor_state/"
                            "mark_node_dead or emit a ClusterEvent "
                            "alongside")
