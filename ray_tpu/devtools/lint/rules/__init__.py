"""Rule families. Importing this package registers every rule."""

from ray_tpu.devtools.lint.rules import (collectives,  # noqa: F401
                                         concurrency, conventions, hygiene,
                                         lifecycle, ownership, phases, retry,
                                         threadguard)
