"""Rule families. Importing this package registers every rule."""

from ray_tpu.devtools.lint.rules import (concurrency, conventions,  # noqa: F401
                                         hygiene, lifecycle, ownership,
                                         phases, retry, threadguard)
