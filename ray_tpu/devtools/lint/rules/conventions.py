"""Framework convention rules (GL006-GL007)."""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ray_tpu.devtools.lint.annotate import FileContext, _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register

_METRIC_NAME_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")
# Unit/kind suffixes accepted per metric type. Counters are cumulative
# and must say so (_total); histograms measure a unit; gauges may also
# be dimensionless levels (_depth, _ratio, _requests...).
_METRIC_SUFFIXES = {
    "Counter": ("_total",),
    "Histogram": ("_seconds", "_bytes", "_size", "_tokens", "_ratio"),
    "Gauge": ("_seconds", "_bytes", "_ratio", "_depth", "_requests",
              "_tokens", "_total", "_size", "_count", "_percent",
              "_occupancy", "_workers", "_nodes", "_replicas", "_mfu",
              "_flag", "_info", "_actors", "_objects", "_tasks",
              "_per_second", "_steps", "_pending", "_fds", "_in_flight"),
}


@register
class MetricNamingConvention(Rule):
    id = "GL006"
    name = "metric-naming-convention"
    rationale = ("every exported metric is `ray_tpu_`-prefixed "
                 "snake_case with a unit/kind suffix (`_total` for "
                 "counters) so dashboards and alerts survive refactors")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            kind = dotted.rsplit(".", 1)[-1]
            if kind not in _METRIC_SUFFIXES:
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if not _METRIC_NAME_RE.match(name):
                yield ctx.finding(
                    self.id, node,
                    f"metric {name!r} is outside the ray_tpu_ "
                    "snake_case convention")
            elif not name.endswith(_METRIC_SUFFIXES[kind]):
                yield ctx.finding(
                    self.id, node,
                    f"{kind} {name!r} lacks a unit/kind suffix "
                    f"(expected one of {_METRIC_SUFFIXES[kind]})")


@register
class TraceContextDrop(Rule):
    id = "GL007"
    name = "trace-context-drop"
    rationale = ("a TaskSpec built without trace_id breaks the "
                 "distributed trace at that hop (PR 1 wired trace "
                 "context end-to-end; new call sites must keep it)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            if dotted.rsplit(".", 1)[-1] != "TaskSpec":
                continue
            kw_names = {k.arg for k in node.keywords}
            if None in kw_names:  # **kwargs may carry it
                continue
            if "trace_id" not in kw_names:
                yield ctx.finding(
                    self.id, node,
                    "TaskSpec(...) without trace_id= — this hop drops "
                    "the request's trace context")
