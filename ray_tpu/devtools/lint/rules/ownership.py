"""Object-ownership rules (GL014-GL017).

The static half of refsan (``ray_tpu/devtools/refsan.py``): these rules
catch lifetime-protocol misuse at the source level — reference
round-trips that skip borrow registration, pins created in loops with
no holder, out-of-band views whose release is not tied to the value's
lifetime, and reference-count state mutated outside its lock-owning
methods. GL015/GL016 are project rules: the drop-in-a-loop and the
lifetime-tie may live one call away, so they walk the interprocedural
call graph (callgraph.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.annotate import (_MUTATORS, _dotted,
                                            _is_self_attr)
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import (Key, ProjectContext, _leaf,
                                             body_nodes)

#: reference-count state owned by ReferenceCounter / the refsan Ledger;
#: mutable only by self, under the owner's lock
_COUNT_ATTRS = {"_counts", "_pins"}


def _contains_binary_call(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and
               isinstance(n.func, ast.Attribute) and
               n.func.attr == "binary"
               for n in ast.walk(node))


def _loop_node_ids(func_node: ast.AST) -> Set[int]:
    """ids of nodes lexically inside a For/While in this function's own
    body (callgraph ``loop_ctx`` is IO-loop-THREAD context — unrelated)."""
    out: Set[int] = set()
    for n in body_nodes(func_node):
        if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
            for sub in body_nodes(n):
                out.add(id(sub))
    return out


@register
class RefFromRawBinary(Rule):
    id = "GL014"
    name = "ref-from-raw-binary"
    rationale = ("ObjectRef(ObjectID(x.binary())) round-trips a "
                 "reference through raw bytes: the bytes carry no "
                 "liveness, so nothing guarantees the object survived "
                 "between binary() and the re-registration — "
                 "serialize the ObjectRef itself (pickling registers "
                 "the borrow) or keep the original ref alive")

    def check(self, ctx) -> Iterator[Finding]:
        # per-function dataflow: names assigned from a .binary() result
        for fn in (n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            tainted: Set[str] = set()
            for n in body_nodes(fn):
                if isinstance(n, ast.Assign) and \
                        _contains_binary_call(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
            for call in body_nodes(fn):
                if not isinstance(call, ast.Call):
                    continue
                if _leaf(_dotted(call.func)) != "ObjectRef":
                    continue
                if not call.args:
                    continue
                arg = call.args[0]
                hit = _contains_binary_call(arg) or any(
                    isinstance(n, ast.Name) and n.id in tainted
                    for n in ast.walk(arg))
                if hit:
                    yield ctx.finding(
                        self.id, call,
                        "ObjectRef constructed from raw binary() bytes "
                        "outside the serialization/borrow-registration "
                        "paths — the owner-side REF_ADD is skipped for "
                        "the window the bytes were in flight; pass the "
                        "ObjectRef itself (pickle registers the borrow)")


@register
class DroppedRefInLoop(Rule):
    id = "GL015"
    name = "dropped-ref-in-loop"
    project = True
    rationale = ("a put()/task-submit result discarded inside a loop "
                 "accumulates owner-side pins with no holder to ever "
                 "release them — keep the refs (and drop them when "
                 "consumed) or don't create the object")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        # (func key, drop site) for bare drops not already in a lexical
        # loop; resolved against callers below (two-hop)
        bare: List[Tuple[Key, ast.Call]] = []
        loop_ids: Dict[Key, Set[int]] = {}
        for key, info in sorted(project.functions.items()):
            loop_ids[key] = _loop_node_ids(info.node)
            for n in body_nodes(info.node):
                if not isinstance(n, ast.Expr) or \
                        not isinstance(n.value, ast.Call):
                    continue
                call = n.value
                what = self._submit_kind(project, key[0], call)
                if what is None:
                    continue
                if id(n) in loop_ids[key] or id(call) in loop_ids[key]:
                    yield info.ctx.finding(
                        self.id, call,
                        f"{what} result dropped on the floor inside a "
                        f"loop in {info.qualname}() — every iteration "
                        "pins an object nobody can release")
                else:
                    bare.append((key, call))
        if not bare:
            return
        # two-hop: the bare drop's enclosing function is itself called
        # from inside a loop in some caller
        callers: Dict[Key, List[Tuple[Key, ast.Call]]] = {}
        for caller, edges in project.calls.items():
            for callee, site in edges:
                callers.setdefault(callee, []).append((caller, site))
        for key, call in bare:
            info = project.functions[key]
            for caller, site in callers.get(key, ()):
                if id(site) not in loop_ids.get(caller, ()):
                    continue
                cq = project.functions[caller].qualname
                yield info.ctx.finding(
                    self.id, call,
                    f"{self._submit_kind(project, key[0], call)} result "
                    f"dropped on the floor in {info.qualname}(), which "
                    f"is called from a loop in {cq}() "
                    f"({cq} -> {info.qualname}) — every iteration pins "
                    "an object nobody can release")
                break

    @staticmethod
    def _submit_kind(project: ProjectContext, path: str,
                     call: ast.Call) -> Optional[str]:
        # a `.remote(...)` leaf fires regardless of the receiver shape
        # (subscripted receivers like pool[i].f.remote() defeat _dotted)
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "remote":
            return "task submit"
        dotted = _dotted(call.func)
        if dotted is None:
            return None
        # only ray_tpu's put(); a bare q.put() is a queue, not a pin
        imports = project._imports.get(path, {})
        base = dotted.split(".", 1)[0]
        imp = imports.get(base)
        if imp is None:
            return None
        module, orig = imp
        resolved = f"{module}.{orig}" if orig else module
        if "." in dotted:
            resolved = resolved + "." + dotted.split(".", 1)[1]
        if resolved in ("ray_tpu.put", "ray_tpu.api.put",
                        "ray_tpu.core.api.put"):
            return "put()"
        return None


@register
class UntiedPinnedView(Rule):
    id = "GL016"
    name = "untied-pinned-view"
    project = True
    rationale = ("deserializing with out-of-band buffers and then "
                 "calling on_release() inline frees the backing store "
                 "pin while the value still holds zero-copy views (the "
                 "PR-11 bug) — tie the release to the value's lifetime "
                 "(weakref.finalize on a from_buffer view, or a "
                 "__buffer__/__del__ provider)")

    #: call leaves that tie a release to a value's lifetime
    _TIE_LEAVES = {"finalize", "from_buffer"}

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for key, info in sorted(project.functions.items()):
            oob_loads = [
                c for c in project.body_calls(info.node)
                if _leaf(_dotted(c.func)) == "loads" and
                any(kw.arg == "buffers" for kw in c.keywords)]
            if not oob_loads:
                continue
            releases = any(
                _leaf(_dotted(c.func) or "") == "on_release"
                for c in project.body_calls(info.node))
            if not releases:
                continue
            if self._has_lifetime_tie(project, key):
                continue
            for c in oob_loads:
                yield info.ctx.finding(
                    self.id, c,
                    f"{info.qualname}() hands out out-of-band buffers "
                    "and calls on_release() inline: the pin dies before "
                    "the zero-copy views do — tie the release to the "
                    "value (weakref.finalize / from_buffer holder / "
                    "__buffer__ provider)")

    def _has_lifetime_tie(self, project: ProjectContext,
                          key: Key) -> bool:
        """The function (or a callee within two hops) builds a
        value-lifetime release: a finalize/from_buffer call or a class
        whose __del__/__buffer__ carries the release."""
        seen: Set[Key] = set()
        frontier = [key]
        for _hop in range(3):   # the function itself + two hops
            nxt: List[Key] = []
            for k in frontier:
                if k in seen:
                    continue
                seen.add(k)
                info = project.functions.get(k)
                if info is None:
                    continue
                for n in ast.walk(info.node):
                    if isinstance(n, ast.Call) and \
                            _leaf(_dotted(n.func)) in self._TIE_LEAVES:
                        return True
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and \
                            n is not info.node and \
                            n.name in ("__del__", "__buffer__"):
                        return True
                nxt.extend(c for c, _site in project.calls.get(k, ()))
            frontier = nxt
        return False


@register
class CountStateMutation(Rule):
    id = "GL017"
    name = "count-state-mutation"
    rationale = ("_counts/_pins are the ReferenceCounter's (and refsan "
                 "Ledger's) private count state: every mutation must go "
                 "through the owner's lock-holding methods, or adds and "
                 "drops race and the deleter fires early/never")

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            target_attr = self._mutated_count_attr(node)
            if target_attr is None:
                continue
            attr_node, is_self = target_attr
            if not is_self:
                yield ctx.finding(
                    self.id, node,
                    f"reference-count state .{attr_node} mutated from "
                    "outside its owning class — go through the "
                    "counter's lock-holding methods")
                continue
            func = getattr(node, "_gl_func", None)
            if func == "__init__" and self._is_rebind(node):
                continue    # initialization of the container itself
            if getattr(node, "_gl_lockdepth", 0) > 0:
                continue    # mutated under the owner's lock
            yield ctx.finding(
                self.id, node,
                f"self.{attr_node} mutated outside a `with self._lock:` "
                "block — count transitions must be lock-ordered or the "
                "deleter can fire early/never")

    @staticmethod
    def _is_rebind(node: ast.AST) -> bool:
        """Plain attribute (re)binding, e.g. ``self._counts = {}`` —
        allowed in __init__ as container creation."""
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            return all(not isinstance(t, ast.Subscript) for t in targets)
        return False

    @staticmethod
    def _mutated_count_attr(
            node: ast.AST) -> Optional[Tuple[str, bool]]:
        """(attr name, is_self_attr) when ``node`` mutates a
        _counts/_pins attribute; None otherwise."""
        def classify(attr_expr: ast.AST) -> Optional[Tuple[str, bool]]:
            if isinstance(attr_expr, ast.Attribute) and \
                    attr_expr.attr in _COUNT_ATTRS:
                return (attr_expr.attr,
                        _is_self_attr(attr_expr) is not None)
            return None

        def from_target(target: ast.AST) -> Optional[Tuple[str, bool]]:
            if isinstance(target, ast.Subscript):
                return classify(target.value)
            return classify(target)

        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            return classify(node.func.value)
        if isinstance(node, (ast.Assign, ast.Delete)):
            for t in node.targets:
                hit = from_target(t)
                if hit is not None:
                    return hit
            return None
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            return from_target(node.target)
        if isinstance(node, ast.AugAssign):
            return from_target(node.target)
        return None
