"""Collective-program rules (GL021-GL023).

The static half of collsan (``ray_tpu/devtools/collsan.py``): these
rules catch cross-rank divergence bugs at the source level — the
classic desync (a collective issued on some ranks only because the
call is guarded by a rank comparison), error-feedback residual
cross-contamination (two collective call sites sharing one literal
``ef_key`` for different tensors), and half-finished ZeRO steps (a
reduce-scatter whose matching all-gather exists on no path of the same
function family). All three are project rules: the guard, the
colliding site, or the missing all-gather may live one call away, so
they walk the interprocedural call graph (callgraph.py) in the GL015
mold.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.annotate import _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import (Key, ProjectContext, _leaf,
                                             body_nodes)

#: host-collective entry points (parallel/collective.py surface)
_HOST_COLLECTIVES = {
    "allreduce", "reduce_scatter_flat", "allgather_flat", "allgather",
    "reducescatter", "broadcast", "barrier",
}
_REDUCE_SCATTER_OPS = {"reduce_scatter_flat", "reducescatter"}
_ALLGATHER_OPS = {"allgather_flat", "allgather"}

#: names whose comparison in a branch condition marks the branch as
#: rank-dependent (ctx.world_rank, self.rank, get_rank()...)
_RANK_NAMES = {"rank", "world_rank", "local_rank", "stage_rank"}
_RANK_CALL_LEAVES = {"get_rank"}


def _collective_op(project: ProjectContext, path: str,
                   call: ast.Call) -> Optional[str]:
    """The host-collective op name when this call site targets the
    collective module (``collective.allreduce(...)`` or a name imported
    from a ``*collective*`` module); None for unrelated same-named
    calls (a ``q.barrier()`` is not a collective)."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    leaf = _leaf(dotted)
    if leaf not in _HOST_COLLECTIVES:
        return None
    imports = project._imports.get(path, {})
    if "." in dotted:
        base = dotted.rsplit(".", 1)[0]
        if "collective" in base:
            return leaf
        imp = imports.get(base.split(".", 1)[0])
        if imp is not None and "collective" in (
                (imp[0] or "") + "." + (imp[1] or "")):
            return leaf
        return None
    imp = imports.get(leaf)
    if imp is not None and "collective" in (imp[0] or ""):
        return leaf
    return None


def _is_rank_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _RANK_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _RANK_NAMES
    if isinstance(node, ast.Call):
        return _leaf(_dotted(node.func)) in _RANK_CALL_LEAVES
    return False


def _rank_condition(test: ast.AST) -> Optional[bool]:
    """None when the If test does not condition on a rank; otherwise
    True for a broadcast-root-style guard (``rank == <const>`` /
    ``not rank``) and False for any other rank comparison."""
    for n in ast.walk(test):
        if isinstance(n, ast.Compare):
            sides = [n.left] + list(n.comparators)
            if any(_is_rank_expr(s) for s in sides):
                return (len(n.ops) == 1 and
                        isinstance(n.ops[0], ast.Eq) and
                        any(isinstance(s, ast.Constant) for s in sides))
    if _is_rank_expr(test):
        return False        # bare truthiness: `if rank:`
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and _is_rank_expr(test.operand):
        return True         # `if not rank:` ≡ rank == 0
    return None


def _branch_node_ids(if_node: ast.If) -> Set[int]:
    """ids of nodes lexically inside either branch of the If (the test
    itself excluded; nested defs excluded — defining a function under a
    rank guard is not executing a collective there)."""
    out: Set[int] = set()
    for child in if_node.body + if_node.orelse:
        out.add(id(child))
        for sub in body_nodes(child):
            out.add(id(sub))
    return out


def _callers_map(project: ProjectContext
                 ) -> Dict[Key, List[Tuple[Key, ast.Call]]]:
    callers: Dict[Key, List[Tuple[Key, ast.Call]]] = {}
    for caller, edges in project.calls.items():
        for callee, site in edges:
            callers.setdefault(callee, []).append((caller, site))
    return callers


@register
class RankDependentCollective(Rule):
    id = "GL021"
    name = "rank-dependent-collective"
    project = True
    rationale = ("a collective inside a branch conditioned on the rank "
                 "runs on some ranks only — the others never enter the "
                 "round and the group hangs (or silently desyncs); "
                 "hoist the collective out of the guard (rank==0-rooted "
                 "broadcast idioms are exempt)")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        # per function: rank-conditioned If regions (node-id set +
        # whether the guard is the rank==0 broadcast-root shape)
        regions: Dict[Key, List[Tuple[Set[int], bool]]] = {}
        # functions containing unguarded collective calls, candidates
        # for the two-hop pass
        bare: List[Tuple[Key, ast.Call, str]] = []
        for key, info in sorted(project.functions.items()):
            regs: List[Tuple[Set[int], bool]] = []
            for n in body_nodes(info.node):
                if isinstance(n, ast.If):
                    root = _rank_condition(n.test)
                    if root is not None:
                        regs.append((_branch_node_ids(n), root))
            regions[key] = regs
            for call in project.body_calls(info.node):
                op = _collective_op(project, key[0], call)
                if op is None:
                    continue
                guard = next((root for ids, root in regs
                              if id(call) in ids), None)
                if guard is None:
                    bare.append((key, call, op))
                elif not (op == "broadcast" and guard):
                    yield info.ctx.finding(
                        self.id, call,
                        f"collective {op}() guarded by a rank-dependent "
                        f"branch in {info.qualname}() — the other ranks "
                        "never enter this round and the group hangs; "
                        "issue the collective on every rank")
        if not bare:
            return
        # two-hop: an unguarded collective in f, where f is called from
        # inside a rank-conditioned branch of some caller g
        callers = _callers_map(project)
        for key, call, op in bare:
            info = project.functions[key]
            for caller, site in callers.get(key, ()):
                guard = next((root for ids, root in regions.get(caller, ())
                              if id(site) in ids), None)
                if guard is None or (op == "broadcast" and guard):
                    continue
                cq = project.functions[caller].qualname
                yield info.ctx.finding(
                    self.id, call,
                    f"collective {op}() in {info.qualname}() is reached "
                    f"through a rank-dependent branch in {cq}() "
                    f"({cq} -> {info.qualname}) — only some ranks enter "
                    "this round; issue the collective on every rank")
                break


@register
class EfKeyCollision(Rule):
    id = "GL022"
    name = "ef-key-collision"
    project = True
    rationale = ("the error-feedback residual persists per (group, "
                 "ef_key): two call sites reducing different tensors "
                 "under one literal key add one tensor's quantization "
                 "error onto the other — give every logical tensor its "
                 "own ef_key")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        # (group literal, ef_key literal) -> call sites with the first
        # positional arg's structural dump as the tensor identity
        sites: Dict[Tuple[str, str],
                    List[Tuple[object, ast.Call, str]]] = {}
        for key, info in sorted(project.functions.items()):
            for call in project.body_calls(info.node):
                if _collective_op(project, key[0], call) is None:
                    continue
                ef = self._const_kw(call, "ef_key")
                if ef is None or not call.args:
                    continue
                group = self._const_kw(call, "group_name") or "default"
                sites.setdefault((group, ef), []).append(
                    (info, call, ast.dump(call.args[0])))
        for (group, ef), hits in sorted(
                sites.items(), key=lambda kv: kv[0]):
            exprs = {expr for _info, _call, expr in hits}
            if len(hits) < 2 or len(exprs) < 2:
                continue
            ordered = sorted(hits, key=lambda h: (h[0].ctx.path,
                                                  h[1].lineno))
            first = ordered[0]
            for info, call, expr in ordered[1:]:
                if expr == first[2]:
                    continue
                yield info.ctx.finding(
                    self.id, call,
                    f"ef_key {ef!r} (group {group!r}) is shared with "
                    f"the collective at {first[0].ctx.path}:"
                    f"{first[1].lineno} but reduces a different tensor "
                    "— error-feedback residuals cross-contaminate; use "
                    "a distinct ef_key per logical tensor")

    @staticmethod
    def _const_kw(call: ast.Call, name: str) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg == name and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        return None


@register
class UnpairedCollective(Rule):
    id = "GL023"
    name = "unpaired-collective"
    project = True
    rationale = ("a reduce-scatter leaves every rank holding 1/world "
                 "of the result: without the matching all-gather "
                 "somewhere in the same function family the full "
                 "tensor is never reassembled and ranks silently "
                 "train on shards")

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        rs_sites: Dict[Key, List[Tuple[ast.Call, str]]] = {}
        ag_funcs: Set[Key] = set()
        for key, info in sorted(project.functions.items()):
            for call in project.body_calls(info.node):
                op = _collective_op(project, key[0], call)
                if op in _REDUCE_SCATTER_OPS:
                    rs_sites.setdefault(key, []).append((call, op))
                elif op in _ALLGATHER_OPS:
                    ag_funcs.add(key)
        if not rs_sites:
            return
        callers = _callers_map(project)
        for key in sorted(rs_sites):
            if self._family_gathers(project, key, ag_funcs, callers):
                continue
            info = project.functions[key]
            for call, op in rs_sites[key]:
                yield info.ctx.finding(
                    self.id, call,
                    f"{op}() in {info.qualname}() has no matching "
                    "allgather on any path in its function family "
                    "(itself, callees within two hops, direct callers "
                    "and their helpers) — every rank keeps only its "
                    "1/world shard; pair it with "
                    "allgather_flat()/allgather()")

    @staticmethod
    def _family_gathers(project: ProjectContext, key: Key,
                        ag_funcs: Set[Key],
                        callers: Dict[Key, List[Tuple[Key, ast.Call]]]
                        ) -> bool:
        """Does the function family around ``key`` reach an allgather:
        the function itself, its callees within two hops, its direct
        callers, or those callers' direct callees (siblings)?"""
        def callee_closure(start: Key, hops: int) -> Set[Key]:
            seen: Set[Key] = set()
            frontier = [start]
            for _hop in range(hops + 1):
                nxt: List[Key] = []
                for k in frontier:
                    if k in seen:
                        continue
                    seen.add(k)
                    nxt.extend(c for c, _site in project.calls.get(k, ()))
                frontier = nxt
            return seen

        family = callee_closure(key, 2)
        for caller, _site in callers.get(key, ()):
            family |= callee_closure(caller, 1)
        return bool(family & ag_funcs)
