"""Error-handling and dependency hygiene rules (GL004-GL005)."""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ray_tpu.devtools.lint.annotate import FileContext, _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register

_LOGGISH = re.compile(r"(?:^|\.)(?:log|logger|logging|warn|warning|"
                      r"error|exception|debug|info|print_exc|print)")


@register
class SwallowedException(Rule):
    id = "GL004"
    name = "swallowed-exception"
    rationale = ("a bare `except:` or `except Exception: pass` hides "
                 "real failures; log it or justify the suppression")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if not self._handled(node):
                    yield ctx.finding(
                        self.id, node,
                        "bare `except:` traps SystemExit/"
                        "KeyboardInterrupt and hides failures")
                continue
            broad = isinstance(node.type, ast.Name) and \
                node.type.id in ("Exception", "BaseException")
            if broad and self._body_is_silent_pass(node) and \
                    not self._handled(node):
                yield ctx.finding(
                    self.id, node,
                    f"`except {node.type.id}: pass` swallows the "
                    "error without logging")

    @staticmethod
    def _body_is_silent_pass(node: ast.ExceptHandler) -> bool:
        return all(isinstance(stmt, ast.Pass) or
                   (isinstance(stmt, ast.Expr) and
                    isinstance(stmt.value, ast.Constant))
                   for stmt in node.body)

    @staticmethod
    def _handled(node: ast.ExceptHandler) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func)
                if dotted and _LOGGISH.search(dotted):
                    return True
        return False


_FORBIDDEN_IMPORTS = ("torch.cuda", "cupy", "nccl", "pynccl", "pycuda",
                      "pynvml", "cuda")


@register
class ForbiddenBackendImport(Rule):
    id = "GL005"
    name = "forbidden-backend-import"
    rationale = ("CUDA backends are compiled out of this TPU-native "
                 "build (BASELINE.md); torch.cuda/nccl/cupy must not "
                 "creep back in")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if self._forbidden(alias.name):
                        yield ctx.finding(
                            self.id, node,
                            f"import of CUDA backend {alias.name!r}")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if self._forbidden(mod):
                    yield ctx.finding(
                        self.id, node,
                        f"import from CUDA backend {mod!r}")
                elif mod == "torch":
                    for alias in node.names:
                        if alias.name == "cuda":
                            yield ctx.finding(
                                self.id, node,
                                "`from torch import cuda` — CUDA is "
                                "compiled out")
            elif isinstance(node, ast.Attribute):
                if _dotted(node) == "torch.cuda":
                    yield ctx.finding(self.id, node,
                                      "use of torch.cuda attribute")

    @staticmethod
    def _forbidden(module: str) -> bool:
        return any(module == root or module.startswith(root + ".")
                   for root in _FORBIDDEN_IMPORTS)
