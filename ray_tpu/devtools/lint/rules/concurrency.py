"""Lock/thread discipline rules (GL001-GL003, GL008)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional

from ray_tpu.devtools.lint.annotate import (FileContext, _MUTATORS,
                                            _dotted, _is_self_attr)
from ray_tpu.devtools.lint.base import Finding, Rule, register


@register
class UnguardedSharedState(Rule):
    id = "GL001"
    name = "unguarded-shared-state"
    rationale = ("a class that owns a lock mutates self._* state "
                 "outside any `with <lock>` block — racy once a second "
                 "thread touches the instance")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            cls = getattr(node, "_gl_class", None)
            if cls is None or not cls._gl_locks:
                continue
            if node._gl_func == "__init__" or node._gl_lockdepth > 0:
                continue
            attr = self._mutated_attr(node, cls)
            if attr is not None:
                names = sorted(cls._gl_locks)
                if len(names) > 3:
                    names = names[:3] + [f"+{len(names) - 3} more"]
                yield ctx.finding(
                    self.id, node,
                    f"mutation of self.{attr} outside the lock "
                    f"({'/'.join(names)}) this class owns")

    @staticmethod
    def _mutated_attr(node: ast.AST, cls) -> Optional[str]:
        def shared(target) -> Optional[str]:
            attr = _is_self_attr(target)
            if attr is not None and attr.startswith("_") \
                    and not attr.startswith("__") \
                    and attr not in cls._gl_locks:
                return attr
            return None

        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            return shared(node.func.value)
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            # read-modify-write on a self attr is racy even for scalars
            target = node.target
            if isinstance(target, ast.Subscript):
                return shared(target.value)
            return shared(target)
        else:
            return None
        for target in targets:
            if isinstance(target, ast.Subscript):
                attr = shared(target.value)
                if attr is not None:
                    return attr
        return None


_BLOCKING_EXACT = {"time.sleep", "ray_tpu.get", "subprocess.run",
                   "subprocess.call", "subprocess.check_call",
                   "subprocess.check_output", "subprocess.Popen",
                   "socket.create_connection"}
_BLOCKING_LEAF = {"sleep", "recv", "recv_into", "accept", "connect",
                  "gcs_call", "wait_for_nodes"}


@register
class LockHeldAcrossBlockingCall(Rule):
    id = "GL002"
    name = "lock-held-across-blocking-call"
    rationale = ("sleeping / socket IO / subprocess / RPC inside a "
                 "`with <lock>` body stalls every thread contending "
                 "for that lock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or node._gl_lockdepth == 0:
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            leaf = dotted.rsplit(".", 1)[-1]
            if dotted in _BLOCKING_EXACT or leaf in _BLOCKING_LEAF or \
                    dotted.startswith("subprocess."):
                yield ctx.finding(
                    self.id, node,
                    f"blocking call {dotted}() while holding a lock")


@register
class BusyWaitLoop(Rule):
    id = "GL003"
    name = "busy-wait-polling-loop"
    rationale = ("`while ...: time.sleep(...)` polling in a class that "
                 "already owns a Condition/Event — use a real wait "
                 "instead of burning wakeups and adding latency")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            cls = getattr(node, "_gl_class", None)
            if cls is None or not cls._gl_events:
                continue
            sleeps, waits = False, False
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if dotted.endswith("time.sleep") or dotted == "sleep":
                    sleeps = True
                if leaf in ("wait", "wait_for", "get", "join"):
                    waits = True
            if sleeps and not waits:
                yield ctx.finding(
                    self.id, node,
                    "busy-wait loop; this class owns "
                    f"{'/'.join(sorted(cls._gl_events))} — wait on it "
                    "instead of polling")


@register
class NonDaemonBackgroundThread(Rule):
    id = "GL008"
    name = "non-daemon-background-thread"
    rationale = ("a non-daemon background thread with no shutdown path "
                 "hangs interpreter exit (tests and drivers never "
                 "terminate)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # collect `<target>.daemon = True` assignments per scope
        daemonized: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                for target in node.targets:
                    if isinstance(target, ast.Attribute) and \
                            target.attr == "daemon":
                        base = _dotted(target.value) or ast.dump(
                            target.value)
                        daemonized.add((node._gl_scope, base))
        assigned_to: Dict[int, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for target in node.targets:
                    base = _dotted(target)
                    if base:
                        assigned_to[id(node.value)] = base
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            if dotted not in ("threading.Thread", "Thread"):
                continue
            kwargs = {k.arg: k.value for k in node.keywords}
            daemon = kwargs.get("daemon")
            if isinstance(daemon, ast.Constant) and daemon.value:
                continue
            if daemon is not None and not isinstance(daemon, ast.Constant):
                continue  # computed daemon-ness: give it the benefit
            target = assigned_to.get(id(node))
            if target and (node._gl_scope, target) in daemonized:
                continue
            yield ctx.finding(
                self.id, node,
                "threading.Thread(...) without daemon=True or a "
                "registered shutdown path")
