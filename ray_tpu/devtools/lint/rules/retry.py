"""Retry-loop rules (GL019).

The static half of the backoff unification (``util/backoff.py``): a
retry loop that re-enters itself from an except handler without any
bounded wait spins hot on a dead link, and a fleet of them (128 node
daemons redialing a restarted head) synchronizes into a thundering
herd. Every such loop must pace itself — ``Backoff``/``jittered`` from
``util/backoff.py``, an Event ``wait``, or at minimum a ``sleep``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.lint.annotate import _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import _leaf

#: a call to any of these (leaf name) paces the loop: stdlib sleeps,
#: Event/Condition waits, selector/socket readiness blocking, and the
#: util/backoff surface
_WAIT_CALLS = {"sleep", "wait", "wait_for", "next_delay", "jittered",
               "Backoff", "select"}

_LOOPS = (ast.While, ast.For)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _iter_same_loop(loop: ast.While, include_test: bool = True):
    """Nodes belonging to THIS loop iteration: the body (and test)
    without descending into nested loops or function definitions — a
    wait or continue in those does not pace/re-enter this loop."""
    stack: list = list(loop.body) + list(loop.orelse)
    if include_test:
        stack.append(loop.test)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _LOOPS + _FUNCS):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _handler_reenters(handler: ast.ExceptHandler) -> bool:
    """True when the except handler can re-enter the loop: an explicit
    ``continue`` at this loop's level (not inside a nested loop)."""
    stack: list = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Continue):
            return True
        if isinstance(node, _LOOPS + _FUNCS):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _loop_waits(loop: ast.While) -> bool:
    for node in _iter_same_loop(loop):
        if not isinstance(node, ast.Call):
            continue
        if _leaf(_dotted(node.func)) in _WAIT_CALLS:
            return True
        # any blocking call given an explicit timeout paces the loop
        # (queue.put(timeout=...), gcs_call(timeout=...), ...)
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
    return False


@register
class UnboundedRetry(Rule):
    id = "GL019"
    name = "unbounded-retry"
    rationale = ("a retry loop whose except handler re-enters it with "
                 "no sleep/wait/backoff anywhere in the loop spins hot "
                 "on a persistent failure, and a fleet of identical "
                 "loops (node daemons redialing a restarted head) "
                 "synchronizes into a thundering herd — pace the loop "
                 "with ray_tpu.util.backoff (Backoff.wait/next_delay or "
                 "jittered), an Event wait, or a deadline-bounded sleep")

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            reenters = any(
                _handler_reenters(h)
                for sub in _iter_same_loop(node, include_test=False)
                if isinstance(sub, ast.Try)
                for h in sub.handlers)
            if not reenters or _loop_waits(node):
                continue
            yield Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message="retry loop re-enters from its except handler "
                        "with no backoff, wait, or sleep — pace it via "
                        "ray_tpu.util.backoff",
                scope=getattr(node, "_gl_scope", "<module>"))
