"""Phase-bracket rules (GL020).

``flight_recorder.phase_begin(cat, name)`` opens an explicit span that
only exists in the journal once the matching ``phase_end`` records it.
A code path that leaves the function between the two (early ``return``
or ``raise``) silently drops the span — the profile table then
under-counts exactly the branch that bailed out, which is usually the
interesting one. The end call belongs in a ``finally`` block (or the
function must have no exit between the pair).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ray_tpu.devtools.lint.annotate import _dotted
from ray_tpu.devtools.lint.base import Finding, Rule, register
from ray_tpu.devtools.lint.callgraph import _leaf

_BEGIN = "phase_begin"
_END = "phase_end"


def _calls_named(fn: ast.AST, name: str) -> List[ast.Call]:
    return [n for n in ast.walk(fn)
            if isinstance(n, ast.Call)
            and _leaf(_dotted(n.func) or "") == name]


def _direct_exits(fn: ast.AST) -> List[ast.AST]:
    """Return/Raise statements belonging to this function (nested
    function bodies excluded — their exits don't leave this frame)."""
    exits: List[ast.AST] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.Return, ast.Raise)):
                exits.append(child)
            visit(child)

    for stmt in fn.body:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            exits.append(stmt)
        visit(stmt)
    return exits


def _finally_linenos(fn: ast.AST) -> Set[int]:
    """Linenos of statements inside any ``finally`` block of this
    function — a phase_end there runs on every path out."""
    out: Set[int] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Try) and n.finalbody:
            for stmt in n.finalbody:
                for sub in ast.walk(stmt):
                    if hasattr(sub, "lineno"):
                        out.add(sub.lineno)
    return out


@register
class UnclosedPhaseBracket(Rule):
    id = "GL020"
    name = "unclosed-phase-bracket"
    rationale = ("a flight-recorder span opened with phase_begin only "
                 "reaches the journal when phase_end records it; an "
                 "early return/raise between the pair silently drops "
                 "the span for exactly the bailing path — close it in "
                 "a finally block")

    def check(self, ctx) -> Iterator[Finding]:
        for fn in (n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            begins = _calls_named(fn, _BEGIN)
            if not begins:
                continue
            end_lines = [c.lineno for c in _calls_named(fn, _END)]
            in_finally = _finally_linenos(fn)
            if any(line in in_finally for line in end_lines):
                continue  # closed on every path out
            first_begin = min(c.lineno for c in begins)
            if not end_lines:
                yield ctx.finding(
                    self.id, begins[0],
                    f"phase_begin at line {first_begin} has no "
                    f"phase_end anywhere in `{fn.name}` — the span "
                    f"never reaches the journal")
                continue
            first_end = min(line for line in end_lines
                            if line >= first_begin) \
                if any(line >= first_begin for line in end_lines) \
                else None
            for node in _direct_exits(fn):
                if node.lineno <= first_begin:
                    continue
                if first_end is not None and node.lineno >= first_end:
                    continue
                kind = ("return" if isinstance(node, ast.Return)
                        else "raise")
                yield ctx.finding(
                    self.id, node,
                    f"early {kind} between phase_begin (line "
                    f"{first_begin}) and its phase_end drops the span "
                    f"on this path — move phase_end into a finally "
                    f"block")
