"""File walking, rule driving, and the CLI.

Per-file rules run against each ``FileContext``; project rules
(``Rule.project = True``) run once against a ``ProjectContext`` built
over every file in the scan, which is how the interprocedural
loop-affinity rules see cross-module call chains. ``lint_file`` wraps
a single file in a one-file project so fixture tests exercise the
project rules too.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu.devtools.lint.annotate import FileContext
from ray_tpu.devtools.lint.base import BASELINE_DEFAULT, Finding, RULES
from ray_tpu.devtools.lint.baseline import (apply_baseline,
                                            find_default_baseline,
                                            load_baseline,
                                            write_baseline)
from ray_tpu.devtools.lint.callgraph import ProjectContext
from ray_tpu.devtools.lint import rules as _rules  # noqa: F401  (registers)


def _iter_py_files(paths: Sequence[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _rel(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:
        rel = path
    if rel.startswith(".." + os.sep):
        rel = path
    return rel.replace(os.sep, "/")


def _parse(path: str, source: Optional[str] = None
           ) -> Tuple[Optional[FileContext], Optional[Finding]]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        return FileContext(_rel(path), source), None
    except SyntaxError as e:
        return None, Finding(rule="GL000", path=_rel(path),
                             line=e.lineno or 1, col=e.offset or 0,
                             message=f"syntax error: {e.msg}",
                             scope="<module>")


def _selected_rules(select: Optional[Iterable[str]],
                    ignore: Optional[Iterable[str]]) -> List[str]:
    selected = set(select) if select else set(RULES)
    if ignore:
        selected -= set(ignore)
    return sorted(selected)


def _run_rules(ctxs: Sequence[FileContext],
               errors: Sequence[Finding],
               select: Optional[Iterable[str]],
               ignore: Optional[Iterable[str]]) -> List[Finding]:
    findings: List[Finding] = list(errors)
    rule_ids = _selected_rules(select, ignore)
    by_path = {ctx.path: ctx for ctx in ctxs}
    for ctx in ctxs:
        for rule_id in rule_ids:
            rule = RULES.get(rule_id)
            if rule is None or rule.project:
                continue
            for finding in rule.check(ctx):
                if not ctx.suppressed(finding):
                    findings.append(finding)
    project_rules = [RULES[r] for r in rule_ids
                     if r in RULES and RULES[r].project]
    if project_rules and ctxs:
        project = ProjectContext(ctxs)
        for rule in project_rules:
            for finding in rule.check_project(project):
                ctx = by_path.get(finding.path)
                if ctx is None or not ctx.suppressed(finding):
                    findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, source: Optional[str] = None,
              select: Optional[Iterable[str]] = None,
              ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    ctx, err = _parse(path, source)
    if ctx is None:
        return [err]
    return _run_rules([ctx], [], select, ignore)


def lint_paths(paths: Sequence[str],
               select: Optional[Iterable[str]] = None,
               ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    ctxs: List[FileContext] = []
    errors: List[Finding] = []
    for path in _iter_py_files(paths):
        ctx, err = _parse(path)
        if ctx is not None:
            ctxs.append(ctx)
        else:
            errors.append(err)
    return _run_rules(ctxs, errors, select, ignore)


# -- output formats ----------------------------------------------------


def _emit_text(findings: Sequence[Finding]) -> None:
    for f in findings:
        print(f)
    if findings:
        by_rule: Dict[str, int] = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items()))
        print(f"graftlint: {len(findings)} finding(s) ({summary})")
    else:
        print("graftlint: clean")


def _emit_json(findings: Sequence[Finding]) -> None:
    payload = [{"rule": f.rule, "path": f.path, "line": f.line,
                "col": f.col, "scope": f.scope, "message": f.message}
               for f in findings]
    json.dump(payload, sys.stdout, indent=1, sort_keys=True)
    sys.stdout.write("\n")


def _emit_github(findings: Sequence[Finding]) -> None:
    # GitHub workflow commands: rendered as inline PR annotations.
    # https://docs.github.com/actions/reference/workflow-commands
    for f in findings:
        msg = f.message.replace("%", "%25").replace("\r", "%0D") \
            .replace("\n", "%0A")
        print(f"::error file={f.path},line={f.line},col={f.col + 1},"
              f"title=graftlint {f.rule}::{msg}")
    if not findings:
        print("::notice::graftlint: clean")


_FORMATS = {"text": _emit_text, "json": _emit_json,
            "github": _emit_github}


# -- CLI ---------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="framework-aware static analysis for ray_tpu")
    parser.add_argument("paths", nargs="*", default=["ray_tpu"])
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: "
                             f"{BASELINE_DEFAULT} in cwd or scanned-"
                             "path ancestors)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring baselines")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings as the baseline "
                             "and exit 0")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run")
    parser.add_argument("--ignore", default=None,
                        help="comma-separated rule ids to skip")
    parser.add_argument("--format", default="text",
                        choices=sorted(_FORMATS),
                        help="output format (default: text; github "
                             "emits workflow-command annotations)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            rule = RULES[rid]
            print(f"{rid} {rule.name}: {rule.rationale}")
        return 0

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    findings = lint_paths(args.paths, select=select, ignore=ignore)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = find_default_baseline(args.paths)

    if args.write_baseline:
        out = baseline_path or BASELINE_DEFAULT
        write_baseline(findings, out)
        print(f"graftlint: wrote {len(findings)} finding(s) to {out}")
        return 0

    if baseline_path and not args.no_baseline:
        findings = apply_baseline(findings, load_baseline(baseline_path))

    _FORMATS[args.format](findings)
    return 1 if findings else 0
