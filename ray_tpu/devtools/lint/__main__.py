import sys

from ray_tpu.devtools.lint.engine import main

if __name__ == "__main__":
    sys.exit(main())
