"""Baseline persistence: grandfathered findings by (file, rule, scope).

File format is byte-compatible with the original single-module
graftlint: ``{"version": 1, "comment": ..., "baseline": {key: count}}``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence

from ray_tpu.devtools.lint.base import BASELINE_DEFAULT, Finding


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}")
    return dict(data.get("baseline", {}))


def write_baseline(findings: Iterable[Finding], path: str) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    payload = {
        "version": 1,
        "comment": ("grandfathered graftlint findings; regenerate with "
                    "`python -m ray_tpu.devtools.lint <paths> "
                    "--write-baseline`. New findings (even in a "
                    "baselined scope) still fail once the scope's "
                    "count is exceeded."),
        "baseline": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Drop up to baseline[key] findings per fingerprint (earliest
    lines win); everything beyond the grandfathered count is new."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            out.append(f)
    return out


def find_default_baseline(paths: Sequence[str]) -> Optional[str]:
    """cwd first, then ancestors of each scanned path."""
    candidates = [os.path.join(os.getcwd(), BASELINE_DEFAULT)]
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p))
        while True:
            candidates.append(os.path.join(d, BASELINE_DEFAULT))
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    for c in candidates:
        if os.path.isfile(c):
            return c
    return None
