"""Interprocedural call graph + loop-thread context propagation.

The IO loop (``ray_tpu/core/io_loop.py``) runs every protocol callback
on ONE ``rtpu-io-loop`` thread; code reachable from those callbacks
must never block, must write metrics via ``record_local``, and owns
its "loop-only" state exclusively. The threadguard rules (GL009-GL012)
need to know *which* functions can run on that thread, which is a
whole-program property — so this module builds a call graph over the
scanned files, seeds "runs-on-loop-thread" contexts from the actual
registration points, and propagates the context breadth-first.

Seeds (a function becomes loop-context when it is):

* passed as a callback to ``call_soon`` / ``call_later`` /
  ``_exec_on_loop`` / ``register_message_conn`` / ``register_listener``
  / ``send_stream`` / a loop-ish ``register`` (receiver mentioning
  io/loop, so ``selector.register`` stays quiet);
* decorated with ``@ray_tpu.devtools.threadguard.loop_only``.

Call edges are resolved conservatively: nested defs in the enclosing
scope chain, same-module functions, ``self.method`` in the enclosing
class, imported module functions/constructors (absolute imports only),
``ClassName.method``, and — as a pragmatic fallback — ``obj._name``
attributes whose ``_name`` is defined exactly once across the scanned
set. Unresolvable calls simply end the walk: the pass is
intra-process, under-approximate by design (no getattr, no
cross-process hops), and exists to catch the easy 95%.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools.lint.annotate import (FileContext, _dotted,
                                            _is_self_attr)

Key = Tuple[str, str]   # (path, qualname)

# leaf callable name -> (positional callback args, callback kwargs)
_SEED_SPECS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "call_soon": ((0,), ()),
    "call_later": ((1,), ()),
    "_exec_on_loop": ((0,), ()),
    "register": ((1, 2), ("on_frames", "on_close")),
    "register_message_conn": ((1, 2), ("on_msg", "on_close")),
    "register_listener": ((1,), ("on_accept",)),
    "send_stream": ((0, 1), ("on_done",)),
}

_METRIC_FACTORIES = {"Counter", "Gauge", "Histogram"}


def _leaf(dotted: Optional[str]) -> str:
    return (dotted or "").rsplit(".", 1)[-1]


def _own_qualname(node: ast.AST) -> str:
    scope = getattr(node, "_gl_scope", "<module>")
    name = getattr(node, "name", None) or f"<lambda:{node.lineno}>"
    return name if scope == "<module>" else f"{scope}.{name}"


def _module_name(path: str) -> Optional[str]:
    """Dotted module for a scanned file; anchored at the last
    ``ray_tpu`` path segment so absolute and relative scan roots
    agree."""
    parts = path.replace("\\", "/").split("/")
    if not parts:
        return None
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if "ray_tpu" in parts:
        idx = len(parts) - 1 - parts[::-1].index("ray_tpu")
        parts = parts[idx:]
    elif parts:
        parts = parts[-1:]
    return ".".join(parts) if parts else None


def body_nodes(func_node: ast.AST) -> Iterator[ast.AST]:
    """Nodes in a function's own body, not descending into nested
    function/class definitions (those are separate graph nodes)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class FuncInfo:
    __slots__ = ("key", "ctx", "node", "qualname", "is_async")

    def __init__(self, key: Key, ctx: FileContext, node: ast.AST):
        self.key = key
        self.ctx = ctx
        self.node = node
        self.qualname = key[1]
        self.is_async = isinstance(node, ast.AsyncFunctionDef)


class ProjectContext:
    """Whole-scan view consumed by project rules (GL009-GL012)."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.ctxs: Dict[str, FileContext] = {c.path: c for c in ctxs}
        self.functions: Dict[Key, FuncInfo] = {}
        self._module_funcs: Dict[str, Dict[str, Key]] = {}
        self._methods: Dict[int, Dict[str, Key]] = {}
        self._nested: Dict[Tuple[str, str], Dict[str, Key]] = {}
        self._classes: Dict[str, Dict[str, ast.ClassDef]] = {}
        self._imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        self._module_paths: Dict[str, str] = {}
        self._lambda_keys: Dict[int, Key] = {}
        self._underscore_index: Dict[str, List[Key]] = {}
        #: names bound to Counter/Gauge/Histogram constructors
        self.metric_globals: Set[str] = set()
        self.metric_attrs: Set[Tuple[str, str]] = set()  # (class, attr)
        #: id(ClassDef) -> attr names declared via @loop_owned
        self.loop_owned: Dict[int, Set[str]] = {}
        self.all_classes: List[Tuple[FileContext, ast.ClassDef]] = []
        self.calls: Dict[Key, List[Tuple[Key, ast.Call]]] = {}
        #: seed description per seeded function
        self.seeds: Dict[Key, str] = {}
        #: loop-context functions -> chain of quals from the seed
        self.loop_ctx: Dict[Key, Tuple[str, ...]] = {}
        #: (path, site node, qualname, reason) for GL012
        self.async_registrations: List[Tuple[str, ast.AST, str, str]] = []
        self._index()
        self._collect_edges()
        self._collect_seeds()
        self._propagate()

    # ------------------------------------------------------- indexing

    def _index(self) -> None:
        for path, ctx in self.ctxs.items():
            mod = _module_name(path)
            if mod:
                self._module_paths.setdefault(mod, path)
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    key = (path, _own_qualname(node))
                    if key in self.functions:
                        continue  # e.g. try/except redefinition
                    self.functions[key] = FuncInfo(key, ctx, node)
                    if isinstance(node, ast.Lambda):
                        self._lambda_keys[id(node)] = key
                        continue
                    cls = getattr(node, "_gl_class", None)
                    enclosing_fn = getattr(node, "_gl_func", None)
                    if enclosing_fn is not None:
                        self._nested.setdefault(
                            (path, node._gl_scope), {})[node.name] = key
                    elif cls is not None:
                        self._methods.setdefault(
                            id(cls), {})[node.name] = key
                    else:
                        self._module_funcs.setdefault(
                            path, {})[node.name] = key
                    if node.name.startswith("_") and \
                            not node.name.startswith("__"):
                        self._underscore_index.setdefault(
                            node.name, []).append(key)
                elif isinstance(node, ast.ClassDef):
                    self.all_classes.append((ctx, node))
                    if getattr(node, "_gl_func", None) is None and \
                            getattr(node, "_gl_class", None) is None:
                        self._classes.setdefault(
                            path, {})[node.name] = node
                    owned = self._owned_decl(node)
                    if owned:
                        self.loop_owned[id(node)] = owned
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        bound = alias.asname or alias.name.split(".")[0]
                        target = alias.name if alias.asname \
                            else alias.name.split(".")[0]
                        self._imports.setdefault(
                            path, {})[bound] = (target, None)
                elif isinstance(node, ast.ImportFrom):
                    if node.level:   # relative imports: out of scope
                        continue
                    for alias in node.names:
                        self._imports.setdefault(path, {})[
                            alias.asname or alias.name] = (
                                node.module or "", alias.name)
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    factory = _leaf(_dotted(node.value.func))
                    if factory in _METRIC_FACTORIES:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.metric_globals.add(t.id)
                            attr = _is_self_attr(t)
                            cls = getattr(node, "_gl_class", None)
                            if attr and cls is not None:
                                self.metric_attrs.add((cls.name, attr))

    @staticmethod
    def _owned_decl(cls: ast.ClassDef) -> Set[str]:
        owned: Set[str] = set()
        for dec in cls.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            if _leaf(_dotted(dec.func)) != "loop_owned":
                continue
            for arg in dec.args:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    owned.add(arg.value)
        return owned

    # ----------------------------------------------------- resolution

    def resolve(self, expr: ast.AST, path: str, scope: str,
                cls: Optional[ast.ClassDef]) -> Optional[Key]:
        """Best-effort: the graph key a callable expression refers to."""
        if isinstance(expr, ast.Lambda):
            return self._lambda_keys.get(id(expr))
        if isinstance(expr, ast.Name):
            return self._resolve_name(expr.id, path, scope)
        if isinstance(expr, ast.Attribute):
            return self._resolve_attr(expr, path, cls)
        return None

    def _resolve_name(self, name: str, path: str,
                      scope: str) -> Optional[Key]:
        s = scope
        while True:
            hit = self._nested.get((path, s), {}).get(name)
            if hit:
                return hit
            if "." not in s:
                break
            s = s.rsplit(".", 1)[0]
        hit = self._module_funcs.get(path, {}).get(name)
        if hit:
            return hit
        imp = self._imports.get(path, {}).get(name)
        if imp and imp[1] is not None:
            tpath = self._module_paths.get(imp[0])
            if tpath:
                hit = self._module_funcs.get(tpath, {}).get(imp[1])
                if hit:
                    return hit
                tcls = self._classes.get(tpath, {}).get(imp[1])
                if tcls is not None:
                    return self._methods.get(id(tcls), {}).get("__init__")
        c = self._classes.get(path, {}).get(name)
        if c is not None:
            return self._methods.get(id(c), {}).get("__init__")
        return None

    def _resolve_attr(self, expr: ast.Attribute, path: str,
                      cls: Optional[ast.ClassDef]) -> Optional[Key]:
        attr = expr.attr
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and cls is not None:
                hit = self._methods.get(id(cls), {}).get(attr)
                if hit:
                    return hit
            imp = self._imports.get(path, {}).get(base.id)
            if imp is not None:
                mod = imp[0] if imp[1] is None else \
                    (f"{imp[0]}.{imp[1]}" if imp[0] else imp[1])
                tpath = self._module_paths.get(mod)
                if tpath:
                    hit = self._module_funcs.get(tpath, {}).get(attr)
                    if hit:
                        return hit
                    tcls = self._classes.get(tpath, {}).get(attr)
                    if tcls is not None:
                        return self._methods.get(
                            id(tcls), {}).get("__init__")
            c = self._classes.get(path, {}).get(base.id)
            if c is not None:
                hit = self._methods.get(id(c), {}).get(attr)
                if hit:
                    return hit
        # pragmatic fallback: a private name defined exactly once in
        # the whole scan resolves to that definition (catches
        # ``server._admit``, ``self._loop._flush_conn``...)
        if attr.startswith("_") and not attr.startswith("__"):
            cands = self._underscore_index.get(attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # ---------------------------------------------------------- edges

    def body_calls(self, func_node: ast.AST) -> Iterator[ast.Call]:
        for n in body_nodes(func_node):
            if isinstance(n, ast.Call):
                yield n

    def _collect_edges(self) -> None:
        for key, info in self.functions.items():
            path = key[0]
            cls = getattr(info.node, "_gl_class", None)
            edges = self.calls.setdefault(key, [])
            for call in self.body_calls(info.node):
                callee = self.resolve(call.func, path, info.qualname, cls)
                if callee is not None and callee != key:
                    edges.append((callee, call))

    # ---------------------------------------------------------- seeds

    @staticmethod
    def _loopish_receiver(func: ast.AST) -> bool:
        if not isinstance(func, ast.Attribute):
            return False
        recv = func.value
        if isinstance(recv, ast.Call):
            return _leaf(_dotted(recv.func)) == "get_io_loop"
        dotted = (_dotted(recv) or "").lower()
        return "io" in dotted.split(".")[-1] or "loop" in dotted

    def _collect_seeds(self) -> None:
        # decorator seeds: @loop_only marks a function loop-context
        for key, info in self.functions.items():
            node = info.node
            for dec in getattr(node, "decorator_list", ()):
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _leaf(_dotted(target)) == "loop_only":
                    self.seeds.setdefault(key, "@loop_only")
                    self._check_async(key, key[0], node,
                                      "@loop_only-decorated")
        # registration seeds
        for path, ctx in self.ctxs.items():
            for call in ast.walk(ctx.tree):
                if not isinstance(call, ast.Call):
                    continue
                leaf = _leaf(_dotted(call.func))
                spec = _SEED_SPECS.get(leaf)
                if spec is None:
                    continue
                if leaf == "register" and \
                        not self._loopish_receiver(call.func):
                    continue
                scope = getattr(call, "_gl_scope", "<module>")
                cls = getattr(call, "_gl_class", None)
                exprs = [call.args[i] for i in spec[0]
                         if i < len(call.args)]
                exprs += [kw.value for kw in call.keywords
                          if kw.arg in spec[1]]
                for expr in exprs:
                    if isinstance(expr, ast.Call):
                        # e.g. send_stream(chunks(), ...): the
                        # generator body runs on the loop thread
                        expr = expr.func
                    key = self.resolve(expr, path, scope, cls)
                    if key is None:
                        continue
                    desc = (f"{leaf}() @ {path}:"
                            f"{getattr(call, 'lineno', 0)}")
                    self.seeds.setdefault(key, desc)
                    self._check_async(key, path, call,
                                      f"registered via {leaf}()")

    def _check_async(self, key: Key, site_path: str, site_node: ast.AST,
                     how: str) -> None:
        info = self.functions.get(key)
        if info is None:
            return
        if info.is_async:
            self.async_registrations.append(
                (site_path, site_node, info.qualname,
                 f"{how} callback {info.qualname}() is `async def`"))
            return
        # sync callback that returns an awaitable (return <async fn>())
        cls = getattr(info.node, "_gl_class", None)
        for n in body_nodes(info.node):
            if isinstance(n, ast.Return) and isinstance(n.value, ast.Call):
                tgt = self.resolve(n.value.func, key[0], info.qualname,
                                   cls)
                if tgt is not None and self.functions[tgt].is_async:
                    self.async_registrations.append(
                        (site_path, site_node, info.qualname,
                         f"{how} callback {info.qualname}() returns an "
                         f"awaitable ({self.functions[tgt].qualname}())"))
                    return

    # ---------------------------------------------------- propagation

    def _propagate(self) -> None:
        from collections import deque
        q = deque()
        for key, desc in self.seeds.items():
            if key in self.functions:
                self.loop_ctx[key] = (desc,)
                q.append(key)
        while q:
            key = q.popleft()
            chain = self.loop_ctx[key]
            qual = self.functions[key].qualname
            for callee, _site in self.calls.get(key, ()):
                if callee not in self.loop_ctx:
                    self.loop_ctx[callee] = chain + (qual,)
                    q.append(callee)

    def chain_str(self, key: Key) -> str:
        chain = self.loop_ctx.get(key, ())
        qual = self.functions[key].qualname
        return " -> ".join(chain + (qual,))
