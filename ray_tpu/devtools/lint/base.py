"""Finding/Rule primitives and the rule registry.

Split out of the original single-module graftlint so rule modules can
import the registry without pulling in the engine (CLI, file walking)
and vice versa.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator

BASELINE_DEFAULT = "graftlint_baseline.json"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str   # posix-style, relative to the scan root when possible
    line: int
    col: int
    message: str
    scope: str  # enclosing "Class.method" qualname ("<module>" at top)

    @property
    def key(self) -> str:
        """Baseline fingerprint: stable across line-number drift."""
        return f"{self.path}::{self.rule}::{self.scope}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


RULES: "Dict[str, Rule]" = {}


def register(cls):
    inst = cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


class Rule:
    id: str = ""
    name: str = ""
    rationale: str = ""
    #: project rules run once over the whole scanned set (with a call
    #: graph) instead of once per file; they implement check_project.
    project: bool = False

    def check(self, ctx) -> Iterator[Finding]:
        if self.project:
            return iter(())
        raise NotImplementedError

    def check_project(self, project) -> Iterator[Finding]:
        raise NotImplementedError
