"""Per-file parse + annotation pass shared by every rule.

One ``FileContext`` per source file: parses once, records suppression
comments, and attaches to every AST node its enclosing scope qualname,
function, class, and lock depth — so individual rules stay small.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from ray_tpu.devtools.lint.base import Finding

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_EVENT_FACTORIES = {"Condition", "Event"}
_LOCKISH_NAME = re.compile(r"(?:^|_)(?:lock|mutex|cv|cond)(?:$|_)|lock$")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:#|$)")

# container methods that mutate in place (shared by GL001/GL011)
_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "setdefault", "__setitem__",
}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class FileContext:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = self._parse_suppressions()
        self._annotate()

    # -- suppression comments -----------------------------------------
    def _parse_suppressions(self) -> Dict[int, set]:
        out: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            if "graftlint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {s.strip().upper() for s in m.group(1).split(",")
                       if s.strip()}
                out[i] = ids
        return out

    def suppressed(self, finding: Finding) -> bool:
        ids = self.suppressions.get(finding.line)
        return bool(ids) and (finding.rule in ids or "ALL" in ids)

    # -- annotation pass ----------------------------------------------
    def _annotate(self) -> None:
        """Attach to every node: ``_gl_scope`` (Class.method qualname),
        ``_gl_func`` (innermost function name or None), ``_gl_class``
        (innermost ClassDef node or None), ``_gl_lockdepth`` (number of
        enclosing ``with <lock>`` blocks). ClassDef nodes additionally
        get ``_gl_locks`` / ``_gl_events`` (self-attribute names bound
        to Lock/RLock/Condition and Condition/Event factories)."""
        for cls in (n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)):
            locks, events = set(), set()
            for sub in ast.walk(cls):
                if not isinstance(sub, ast.Assign):
                    continue
                call = sub.value
                if not isinstance(call, ast.Call):
                    continue
                factory = _dotted(call.func) or ""
                leaf = factory.rsplit(".", 1)[-1]
                for target in sub.targets:
                    attr = _is_self_attr(target)
                    if attr is None:
                        continue
                    if leaf in _LOCK_FACTORIES or \
                            leaf in ("traced_lock", "traced_rlock"):
                        locks.add(attr)
                    if leaf in _EVENT_FACTORIES:
                        events.add(attr)
            cls._gl_locks = locks
            cls._gl_events = events

        def visit(node, scope, func, cls, lockdepth):
            node._gl_scope = scope
            node._gl_func = func
            node._gl_class = cls
            node._gl_lockdepth = lockdepth
            if isinstance(node, ast.ClassDef):
                scope = node.name if scope == "<module>" \
                    else f"{scope}.{node.name}"
                cls = node
                func = None
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = node.name if scope == "<module>" \
                    else f"{scope}.{node.name}"
                func = node.name
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                if any(self.is_lock_expr(item.context_expr, cls)
                       for item in node.items):
                    lockdepth += 1
            for child in ast.iter_child_nodes(node):
                visit(child, scope, func, cls, lockdepth)

        visit(self.tree, "<module>", None, None, 0)

    def is_lock_expr(self, expr: ast.AST, cls) -> bool:
        """Heuristic: does ``with <expr>:`` acquire a lock? True for
        self-attributes the class binds to a Lock factory, and for any
        name/attribute that *looks* like a lock (``_lock``, ``cv``,
        ``mutex``...)."""
        attr = _is_self_attr(expr)
        if attr is not None:
            if cls is not None and attr in getattr(cls, "_gl_locks", ()):
                return True
            return bool(_LOCKISH_NAME.search(attr))
        if isinstance(expr, ast.Name):
            return bool(_LOCKISH_NAME.search(expr.id))
        if isinstance(expr, ast.Attribute):
            return bool(_LOCKISH_NAME.search(expr.attr))
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       scope=getattr(node, "_gl_scope", "<module>"))
