"""graftlint: an AST rule engine for ray_tpu's thread-based control
plane.

The control plane guards its shared state with ~70 ``threading.Lock``
sites; at production scale the bottleneck is silent races and
deadlocks, not throughput (Podracer, arXiv:2104.06272; MPMD pipeline
schedulers, arXiv:2412.14374). Generic linters can't see framework
conventions — which classes own locks, what a TaskSpec must carry,
what a metric must be named, which functions run on the single
rtpu-io-loop thread — so this engine ships framework-specific rules
and grows with the codebase.

Usage::

    python -m ray_tpu.devtools.lint [paths...]
    python -m ray_tpu.devtools.lint ray_tpu/ --write-baseline
    python -m ray_tpu.devtools.lint ray_tpu/ --format=github

Findings are suppressed three ways:

* per-line: a ``# graftlint: disable=GL004`` comment on the reported
  line (comma-separate several ids; ``disable=all`` kills every rule);
* baseline: a checked-in ``graftlint_baseline.json`` grandfathers
  existing findings by (file, rule, enclosing scope) — line drift
  does not invalidate it; NEW findings in a scope still fail;
* ``--select``/``--ignore`` on the command line.

Rules are plain classes in a registry; add one by subclassing
``Rule`` and decorating with ``@register``. Per-file rules implement
``check(ctx)``; interprocedural rules set ``project = True`` and
implement ``check_project(project)`` against the call-graph
``ProjectContext`` (see ``callgraph.py``).

Package layout (was a single module through PR 8):

* ``base.py``      — Finding, Rule, registry
* ``annotate.py``  — FileContext: one parse + annotation pass
* ``callgraph.py`` — interprocedural loop-context propagation
* ``baseline.py``  — grandfathered-finding persistence
* ``rules/``       — one module per rule family
* ``engine.py``    — file walking, rule driving, CLI
"""

from ray_tpu.devtools.lint.annotate import (FileContext, _dotted,  # noqa: F401
                                            _is_self_attr)
from ray_tpu.devtools.lint.base import (BASELINE_DEFAULT, Finding,  # noqa: F401
                                        RULES, Rule, register)
from ray_tpu.devtools.lint.baseline import (apply_baseline,  # noqa: F401
                                            find_default_baseline,
                                            load_baseline,
                                            write_baseline)
from ray_tpu.devtools.lint.callgraph import ProjectContext  # noqa: F401
from ray_tpu.devtools.lint.engine import (lint_file,  # noqa: F401
                                          lint_paths, main)

__all__ = [
    "BASELINE_DEFAULT", "FileContext", "Finding", "ProjectContext",
    "RULES", "Rule", "apply_baseline", "find_default_baseline",
    "lint_file", "lint_paths", "load_baseline", "main", "register",
    "write_baseline",
]
