"""ray_tpu.dag — static DAGs over actors, optionally compiled onto
pre-established shared-memory channels (reference: python/ray/dag/)."""

from ray_tpu.dag.compiled import (
    CompiledDAG, CompiledDAGRef, DAGExecutionError)
from ray_tpu.dag.node import (
    ClassMethodNode, DAGNode, FunctionNode, InputNode, MultiOutputNode)

__all__ = [
    "ClassMethodNode", "CompiledDAG", "CompiledDAGRef", "DAGExecutionError",
    "DAGNode", "FunctionNode", "InputNode", "MultiOutputNode",
]
