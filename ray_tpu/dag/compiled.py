"""Compiled graphs: a bound DAG pinned onto its actors with
pre-established shared-memory channels.

Reference: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG) —
compile once, then each `execute()` moves data through pre-allocated
channels with NO per-call task submission, scheduling, or control-plane
RPC. Each participating actor runs a resident execution loop (installed
via the `__ray_call__` escape hatch) that polls its input channels,
runs its nodes in topo order, and writes output channels; the driver
only touches the shm arena.

Error/teardown semantics match the reference: application exceptions
flow through the channels as error tokens (the DAG stays alive);
`teardown()` injects a stop token that propagates through every
channel and unwinds the loops.

Channel transport is chosen per edge at compile time: co-located
writer/readers share a shm ring (zero-copy); edges that cross nodes
move over pre-established worker-to-worker TCP links with credit-based
backpressure (dag/tcp_channel.py) — the DCN analog of the reference's
NCCL channels (experimental/channel/nccl_group.py:21), with reader
listeners created before loop install so connects can't race.
"""

from __future__ import annotations

import copy
import logging
import os
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import ChannelReader, ChannelSpec, ChannelWriter
from ray_tpu.dag.node import (
    ClassMethodNode, DAGNode, FunctionNode, InputAttributeNode, InputNode,
    MultiOutputNode)

logger = logging.getLogger(__name__)


class _Stop:
    """Teardown token."""


class _ErrorToken:
    def __init__(self, error: BaseException, node_name: str):
        self.error = error
        self.node_name = node_name


_STOP = _Stop()


class DAGExecutionError(RuntimeError):
    pass


def _make_reader(entry):
    if entry[0] == "shm":
        return ChannelReader(entry[1], entry[2])
    from ray_tpu.dag.tcp_channel import adopt_listener
    return adopt_listener(entry[1])  # ("tcp", token)


def _make_writer(entry):
    if entry[0] == "shm":
        return ChannelWriter(entry[1])
    from ray_tpu.dag.tcp_channel import TcpChannelWriter
    return TcpChannelWriter(entry[1], entry[2])  # ("tcp", endpoints, cap)


def _create_listener(instance, token):
    """__ray_call__ helper: reader-side TCP endpoint, pre-install."""
    from ray_tpu.dag.tcp_channel import create_listener
    return create_listener(token)


def _close_listener(instance, token):
    """__ray_call__ helper: reclaim a never-adopted listener after a
    failed compile (otherwise its bound socket leaks in the actor
    process registry for the actor's lifetime)."""
    from ray_tpu.dag import tcp_channel
    with tcp_channel._registry_lock:
        listener = tcp_channel._listener_registry.pop(token, None)
    if listener is not None:
        listener.close()


def _compiled_dag_loop(instance, schedule):
    """Resident per-actor loop. Reads lazily (just before the first
    node that needs a channel) so actor-level cycles like
    A.n1 -> B.n2 -> A.n3 can't deadlock."""
    readers = {key: _make_reader(entry)
               for key, entry in schedule["reads"].items()}
    writers = {uid: _make_writer(entry)
               for uid, entry in schedule["writes"].items()}
    zero_copy = schedule.get("zero_copy", False)
    seq = 0
    while True:
        cache: Dict[str, Any] = {}
        stop = False

        def read(key):
            nonlocal stop
            if key not in cache:
                value = readers[key].read(seq, timeout=None)
                # Channel reads are zero-copy views into slots the writer
                # reuses after `capacity` executions; hand user methods an
                # owned copy so a stateful actor retaining its input never
                # sees the slot rewritten underneath it. Opt out via
                # experimental_compile(zero_copy_reads=True) when no
                # method retains its inputs (saves an O(payload) copy per
                # hop).
                if (not zero_copy
                        and not getattr(readers[key], "owned_reads",
                                        False)
                        and not isinstance(value, (_Stop, _ErrorToken))):
                    value = copy.deepcopy(value)
                cache[key] = value
            value = cache[key]
            if isinstance(value, _Stop):
                stop = True
            return value

        local: Dict[int, Any] = {}
        for node in schedule["nodes"]:
            error: Optional[_ErrorToken] = None

            def resolve(aspec):
                nonlocal error
                kind = aspec[0]
                if kind == "const":
                    return aspec[1]
                if kind == "local":
                    value = local[aspec[1]]
                else:  # ("chan", key, selector)
                    value = read(aspec[1])
                    if stop:
                        return None
                    if aspec[1] == "__input__" and \
                            not isinstance(value, _ErrorToken):
                        in_args, in_kwargs = value
                        value = InputNode.extract(aspec[2], in_args,
                                                  in_kwargs)
                if isinstance(value, _ErrorToken):
                    error = value
                return value

            if node.get("sync_input"):
                read("__input__")
            if stop:
                break
            args = [resolve(a) for a in node["args"]]
            kwargs = {k: resolve(v) for k, v in node["kwargs"].items()}
            if stop:
                break
            uid = node["uid"]
            if error is not None:
                local[uid] = error
            else:
                try:
                    method = getattr(instance, node["method"])
                    local[uid] = method(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 — user code
                    local[uid] = _ErrorToken(e, node["method"])
            if uid in writers:
                # block on backpressure indefinitely: a slow driver must
                # stall the pipeline, not kill it
                writers[uid].write(local[uid], seq, timeout=None)

        if not stop:
            for key in readers:
                read(key)  # drain channels untouched this round
        if stop:
            for writer in writers.values():
                writer.write(_STOP, seq, timeout=None)
            for key in cache:
                readers[key].ack(seq)
            for endpoint in list(readers.values()) + list(writers.values()):
                close = getattr(endpoint, "close", None)
                if close is not None:  # TCP endpoints hold sockets
                    close()
            return seq
        for key in readers:
            readers[key].ack(seq)
        seq += 1


class CompiledDAGRef:
    """Future for one `execute()`; `get()` reads the output channels."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value: Any = None
        self._fetched = False

    def get(self, timeout: Optional[float] = 60.0):
        if not self._fetched:
            self._value = self._dag._read_output(self._seq, timeout)
            self._fetched = True
        if isinstance(self._value, _ErrorToken):
            from ray_tpu.util import flight_recorder
            from ray_tpu.devtools import recovery
            # post-mortem: the failing node attached its flight-
            # recorder tail at raise time (it rode the pickled
            # exception's __dict__) — surface what the stage was doing,
            # plus any cluster incident (node/worker death) that just
            # happened: a DAG stage dying with its host can't name the
            # event seq that killed it, but the timing attributes it
            raise DAGExecutionError(
                f"node {self._value.node_name!r} failed: "
                f"{self._value.error!r}"
                + flight_recorder.tail_text(self._value.error)
                + recovery.recent_incident_text()
            ) from self._value.error
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_capacity: int = 4,
                 zero_copy_reads: bool = False):
        self._capacity = buffer_capacity
        self._zero_copy_reads = zero_copy_reads
        nodes = root.topo_sort()
        if any(isinstance(n, FunctionNode) for n in nodes):
            raise ValueError(
                "compiled graphs support actor methods only; wrap "
                "stateless functions in an actor (reference behavior)")
        inputs = [n for n in nodes if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG has at most one InputNode")
        self._outputs = (root._outputs if isinstance(root, MultiOutputNode)
                         else [root])
        self._multi = isinstance(root, MultiOutputNode)
        compute = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not compute:
            raise ValueError("DAG has no actor-method nodes")
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise ValueError("DAG outputs must be actor-method nodes")

        # consumers of each produced value, and of the input
        by_uid = {n._node_uid: n for n in nodes}
        actor_of = {n._node_uid: n._handle._actor_id for n in compute}
        consumers: Dict[int, set] = {n._node_uid: set() for n in compute}
        input_consumers: set = set()
        for n in compute:
            for arg in n._all_args():
                if isinstance(arg, ClassMethodNode) and \
                        actor_of[arg._node_uid] != actor_of[n._node_uid]:
                    consumers[arg._node_uid].add(actor_of[n._node_uid])
                elif isinstance(arg, (InputNode, InputAttributeNode)):
                    input_consumers.add(actor_of[n._node_uid])
            # source nodes sync on the input channel for stop/backpressure
            if not any(isinstance(a, DAGNode) for a in n._all_args()):
                input_consumers.add(actor_of[n._node_uid])

        out_uids = {o._node_uid for o in self._outputs}

        def make_spec(uid: Optional[int], reader_actors: set,
                      driver_reads: bool) -> ChannelSpec:
            return ChannelSpec(
                channel_id=os.urandom(8),
                num_readers=len(reader_actors) + (1 if driver_reads else 0),
                capacity=buffer_capacity)

        # channel per cross-actor-consumed or terminal node, + input
        self._chan_specs: Dict[int, ChannelSpec] = {}
        reader_order: Dict[int, List] = {}
        for n in compute:
            uid = n._node_uid
            drv = uid in out_uids
            if consumers[uid] or drv:
                self._chan_specs[uid] = make_spec(uid, consumers[uid], drv)
                reader_order[uid] = sorted(consumers[uid],
                                           key=lambda a: a.hex())
        self._input_spec = make_spec(None, input_consumers, False)
        input_reader_order = sorted(input_consumers, key=lambda a: a.hex())

        def reader_idx(uid: Optional[int], actor_id) -> int:
            order = (input_reader_order if uid is None
                     else reader_order[uid])
            return order.index(actor_id)

        # per-actor schedules
        handles: Dict[Any, Any] = {}
        schedules: Dict[Any, dict] = {}
        for n in compute:
            aid = actor_of[n._node_uid]
            handles[aid] = n._handle
            schedules.setdefault(aid, {"reads": {}, "writes": {},
                                       "nodes": [],
                                       "zero_copy": zero_copy_reads})
        for n in compute:
            aid = actor_of[n._node_uid]
            sched = schedules[aid]

            def argspec(arg):
                if isinstance(arg, InputNode):
                    sched["reads"]["__input__"] = (
                        self._input_spec, reader_idx(None, aid))
                    return ("chan", "__input__", None)
                if isinstance(arg, InputAttributeNode):
                    sched["reads"]["__input__"] = (
                        self._input_spec, reader_idx(None, aid))
                    return ("chan", "__input__", arg._selector)
                if isinstance(arg, ClassMethodNode):
                    uid = arg._node_uid
                    if actor_of[uid] == aid:
                        return ("local", uid)
                    key = f"n{uid}"
                    sched["reads"][key] = (self._chan_specs[uid],
                                           reader_idx(uid, aid))
                    return ("chan", key, None)
                if isinstance(arg, DAGNode):
                    raise ValueError(f"unsupported node type {type(arg)}")
                return ("const", arg)

            entry = {
                "uid": n._node_uid,
                "method": n._method_name,
                "args": [argspec(a) for a in n._bound_args],
                "kwargs": {k: argspec(v)
                           for k, v in n._bound_kwargs.items()},
                "sync_input": not any(isinstance(a, DAGNode)
                                      for a in n._all_args()),
            }
            if entry["sync_input"]:
                sched["reads"]["__input__"] = (
                    self._input_spec, reader_idx(None, aid))
            if n._node_uid in self._chan_specs:
                sched["writes"][n._node_uid] = self._chan_specs[n._node_uid]
            sched["nodes"].append(entry)

        # --- transport assignment ---------------------------------------
        # A channel stays on the shm ring only when the writer and EVERY
        # reader share one arena (same node; driver endpoints live on
        # the head node). Otherwise the whole channel moves to
        # pre-established worker-to-worker TCP links (dag/tcp_channel.py
        # — the DCN analog of the reference's NCCL channels), readers'
        # listeners created before loop install so connects can't race.
        import time as _time
        import ray_tpu
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        placement: Dict[Any, Any] = {}
        if getattr(rt, "is_driver", False):
            deadline = _time.monotonic() + 10.0
            for aid in handles:
                while True:
                    info = rt.actors.get(aid)
                    if info is not None and info.node_id is not None:
                        break
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"actor {aid} not placed within 10s; cannot "
                            "compile DAG")
                    _time.sleep(0.01)
                placement[aid] = info.node_id
            driver_node = rt.head_node_id
        else:
            driver_node = None  # worker-driven compile: same-node only

        def chan_is_local(writer_node, reader_aids, driver_reads) -> bool:
            if driver_node is None:
                # worker-compiled DAG: placement unknown; keep the
                # pre-existing same-arena behavior
                return True
            nodes_involved = {writer_node}
            nodes_involved.update(placement.get(a) for a in reader_aids)
            if driver_reads:
                nodes_involved.add(driver_node)
            return len(nodes_involved) == 1 and None not in nodes_involved

        def tcp_token(uid, aid) -> str:
            tag = "input" if uid is None else str(uid)
            peer = aid if isinstance(aid, str) else aid.hex()
            return f"dag:{id(self)}:{tag}:{peer}"

        # rewrite schedule entries with transports; collect listener
        # requests per reader actor, then resolve endpoints in one pass
        listener_reqs: List = []  # (aid, token)

        def assign(uid, writer_node, reader_aids, driver_reads):
            if chan_is_local(writer_node, reader_aids, driver_reads):
                return "shm"
            for aid in reader_aids:
                listener_reqs.append((aid, tcp_token(uid, aid)))
            return "tcp"

        input_transport = assign(None, driver_node, input_reader_order,
                                 False)
        chan_transport: Dict[int, str] = {}
        for n in compute:
            uid = n._node_uid
            if uid in self._chan_specs:
                writer_node = placement.get(actor_of[uid])
                chan_transport[uid] = assign(
                    uid, writer_node, reader_order[uid],
                    uid in out_uids)

        endpoints: Dict[str, tuple] = {}
        if listener_reqs:
            refs = [handles[aid].__ray_call__.remote(_create_listener,
                                                     token)
                    for aid, token in listener_reqs]
            try:
                for (aid, token), addr in zip(listener_reqs,
                                              ray_tpu.get(refs)):
                    endpoints[token] = tuple(addr)
            except Exception:
                # partial success: reclaim already-created listeners so
                # repeated failed compiles can't leak actor-side sockets
                for aid, token in listener_reqs:
                    try:
                        # fire-and-forget close nudge: the completed
                        # result is reclaimed by the owner after the
                        # borrow grace window (runtime completion path)
                        handles[aid].__ray_call__.remote(  # graftlint: disable=GL015
                            _close_listener, token)
                    except Exception:  # noqa: BLE001 — reclaim sweep
                        logger.debug("listener reclaim failed on actor "
                                     "%s", aid, exc_info=True)
                raise
        # driver-read TCP outputs: local listeners, created pre-install
        self._driver_tcp_readers: Dict[int, Any] = {}
        from ray_tpu.dag.tcp_channel import (
            TcpChannelListener, TcpChannelReader, TcpChannelWriter)
        for o in self._outputs:
            uid = o._node_uid
            if chan_transport.get(uid) == "tcp":
                # driver address must be reachable from the writer's
                # host; hostname resolution covers LAN and localhost
                listener = TcpChannelListener()
                endpoints[tcp_token(uid, "driver")] = listener.address
                self._driver_tcp_readers[uid] = TcpChannelReader(listener)

        def reader_entry(uid, spec, idx, aid):
            transport = (input_transport if uid is None
                         else chan_transport[uid])
            if transport == "shm":
                return ("shm", spec, idx)
            return ("tcp", tcp_token(uid, aid))

        def writer_entry(uid, spec, reader_aids, driver_reads):
            transport = (input_transport if uid is None
                         else chan_transport[uid])
            if transport == "shm":
                return ("shm", spec)
            eps = [endpoints[tcp_token(uid, a)] for a in reader_aids]
            if driver_reads:
                eps.append(endpoints[tcp_token(uid, "driver")])
            return ("tcp", eps, spec.capacity)

        for aid, sched in schedules.items():
            new_reads = {}
            for key, (spec, idx) in sched["reads"].items():
                uid = None if key == "__input__" else int(key[1:])
                new_reads[key] = reader_entry(uid, spec, idx, aid)
            sched["reads"] = new_reads
            new_writes = {}
            for uid, spec in sched["writes"].items():
                new_writes[uid] = writer_entry(
                    uid, spec, reader_order[uid], uid in out_uids)
            sched["writes"] = new_writes

        # driver-side endpoints
        if input_transport == "shm":
            self._input_writer = ChannelWriter(self._input_spec)
        else:
            eps = [endpoints[tcp_token(None, a)]
                   for a in input_reader_order]
            self._input_writer = None  # connected after install below
            self._pending_input_eps = eps
        self._output_readers = []
        for o in self._outputs:
            uid = o._node_uid
            if chan_transport.get(uid) == "tcp":
                self._output_readers.append(self._driver_tcp_readers[uid])
            else:
                spec = self._chan_specs[uid]
                self._output_readers.append(
                    ChannelReader(spec, spec.num_readers - 1))
        self._next_seq = 0
        self._torn_down = False

        # install the loops (reader listeners already exist, so writer
        # connects inside the loops can't race)
        self._loop_refs = [
            handles[aid].__ray_call__.remote(_compiled_dag_loop, sched)
            for aid, sched in schedules.items()]
        if self._input_writer is None:
            self._input_writer = TcpChannelWriter(
                self._pending_input_eps, self._input_spec.capacity)

    # ------------------------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        seq = self._next_seq
        self._next_seq += 1
        self._input_writer.write((args, kwargs), seq)
        return CompiledDAGRef(self, seq)

    def _read_output(self, seq: int, timeout: Optional[float]):
        # read everything before acking anything, so a timeout on one
        # output leaves the whole seq re-readable
        raw = [reader.read(seq, timeout)
               for reader in self._output_readers]
        # deep-copy shm reads: they may be zero-copy views into slots
        # the writer reuses after `capacity` more executions (TCP reads
        # deserialize into owned objects — no copy needed)
        values = [v if (isinstance(v, _ErrorToken)
                        or getattr(reader, "owned_reads", False))
                  else copy.deepcopy(v)
                  for reader, v in zip(self._output_readers, raw)]
        for reader in self._output_readers:
            reader.ack(seq)
        errors = [v for v in values if isinstance(v, _ErrorToken)]
        if errors:
            return errors[0]
        return values if self._multi else values[0]

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu
        try:
            self._input_writer.write(_STOP, self._next_seq)
        except Exception:  # noqa: BLE001 — a dead reader (lost node)
            # must not abort teardown: still join loops + close sockets
            logger.debug("stop token not delivered during DAG teardown",
                         exc_info=True)
        try:
            ray_tpu.get(self._loop_refs, timeout=30.0)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            logger.debug("DAG actor loops did not join cleanly",
                         exc_info=True)
        for endpoint in ([self._input_writer]
                         + list(self._output_readers)):
            close = getattr(endpoint, "close", None)
            if close is not None:  # TCP endpoints hold sockets
                try:
                    close()
                except Exception:  # noqa: BLE001 — socket already gone
                    logger.debug("DAG channel close failed",
                                 exc_info=True)

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # graftlint: disable=GL004  # interpreter shutdown: logging/runtime may already be torn down, nowhere safe to report
            pass
