"""Compiled graphs: a bound DAG pinned onto its actors with
pre-established shared-memory channels.

Reference: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG) —
compile once, then each `execute()` moves data through pre-allocated
channels with NO per-call task submission, scheduling, or control-plane
RPC. Each participating actor runs a resident execution loop (installed
via the `__ray_call__` escape hatch) that polls its input channels,
runs its nodes in topo order, and writes output channels; the driver
only touches the shm arena.

Error/teardown semantics match the reference: application exceptions
flow through the channels as error tokens (the DAG stays alive);
`teardown()` injects a stop token that propagates through every
channel and unwinds the loops.

Same-node only in this round: channels need writer and readers on one
shm arena (the head node). Cross-slice DAGs ride DCN in the reference
via NCCL channels; the TPU equivalent (jax transfer-server channels)
is future work.
"""

from __future__ import annotations

import copy
import os
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import ChannelReader, ChannelSpec, ChannelWriter
from ray_tpu.dag.node import (
    ClassMethodNode, DAGNode, FunctionNode, InputAttributeNode, InputNode,
    MultiOutputNode)


class _Stop:
    """Teardown token."""


class _ErrorToken:
    def __init__(self, error: BaseException, node_name: str):
        self.error = error
        self.node_name = node_name


_STOP = _Stop()


class DAGExecutionError(RuntimeError):
    pass


def _compiled_dag_loop(instance, schedule):
    """Resident per-actor loop. Reads lazily (just before the first
    node that needs a channel) so actor-level cycles like
    A.n1 -> B.n2 -> A.n3 can't deadlock."""
    readers = {key: ChannelReader(spec, idx)
               for key, (spec, idx) in schedule["reads"].items()}
    writers = {uid: ChannelWriter(spec)
               for uid, spec in schedule["writes"].items()}
    zero_copy = schedule.get("zero_copy", False)
    seq = 0
    while True:
        cache: Dict[str, Any] = {}
        stop = False

        def read(key):
            nonlocal stop
            if key not in cache:
                value = readers[key].read(seq, timeout=None)
                # Channel reads are zero-copy views into slots the writer
                # reuses after `capacity` executions; hand user methods an
                # owned copy so a stateful actor retaining its input never
                # sees the slot rewritten underneath it. Opt out via
                # experimental_compile(zero_copy_reads=True) when no
                # method retains its inputs (saves an O(payload) copy per
                # hop).
                if not zero_copy and not isinstance(
                        value, (_Stop, _ErrorToken)):
                    value = copy.deepcopy(value)
                cache[key] = value
            value = cache[key]
            if isinstance(value, _Stop):
                stop = True
            return value

        local: Dict[int, Any] = {}
        for node in schedule["nodes"]:
            error: Optional[_ErrorToken] = None

            def resolve(aspec):
                nonlocal error
                kind = aspec[0]
                if kind == "const":
                    return aspec[1]
                if kind == "local":
                    value = local[aspec[1]]
                else:  # ("chan", key, selector)
                    value = read(aspec[1])
                    if stop:
                        return None
                    if aspec[1] == "__input__" and \
                            not isinstance(value, _ErrorToken):
                        in_args, in_kwargs = value
                        value = InputNode.extract(aspec[2], in_args,
                                                  in_kwargs)
                if isinstance(value, _ErrorToken):
                    error = value
                return value

            if node.get("sync_input"):
                read("__input__")
            if stop:
                break
            args = [resolve(a) for a in node["args"]]
            kwargs = {k: resolve(v) for k, v in node["kwargs"].items()}
            if stop:
                break
            uid = node["uid"]
            if error is not None:
                local[uid] = error
            else:
                try:
                    method = getattr(instance, node["method"])
                    local[uid] = method(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 — user code
                    local[uid] = _ErrorToken(e, node["method"])
            if uid in writers:
                # block on backpressure indefinitely: a slow driver must
                # stall the pipeline, not kill it
                writers[uid].write(local[uid], seq, timeout=None)

        if not stop:
            for key in readers:
                read(key)  # drain channels untouched this round
        if stop:
            for writer in writers.values():
                writer.write(_STOP, seq, timeout=None)
            for key in cache:
                readers[key].ack(seq)
            return seq
        for key in readers:
            readers[key].ack(seq)
        seq += 1


class CompiledDAGRef:
    """Future for one `execute()`; `get()` reads the output channels."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value: Any = None
        self._fetched = False

    def get(self, timeout: Optional[float] = 60.0):
        if not self._fetched:
            self._value = self._dag._read_output(self._seq, timeout)
            self._fetched = True
        if isinstance(self._value, _ErrorToken):
            raise DAGExecutionError(
                f"node {self._value.node_name!r} failed: "
                f"{self._value.error!r}") from self._value.error
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_capacity: int = 4,
                 zero_copy_reads: bool = False):
        self._capacity = buffer_capacity
        self._zero_copy_reads = zero_copy_reads
        nodes = root.topo_sort()
        if any(isinstance(n, FunctionNode) for n in nodes):
            raise ValueError(
                "compiled graphs support actor methods only; wrap "
                "stateless functions in an actor (reference behavior)")
        inputs = [n for n in nodes if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG has at most one InputNode")
        self._outputs = (root._outputs if isinstance(root, MultiOutputNode)
                         else [root])
        self._multi = isinstance(root, MultiOutputNode)
        compute = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not compute:
            raise ValueError("DAG has no actor-method nodes")
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise ValueError("DAG outputs must be actor-method nodes")

        # consumers of each produced value, and of the input
        by_uid = {n._node_uid: n for n in nodes}
        actor_of = {n._node_uid: n._handle._actor_id for n in compute}
        consumers: Dict[int, set] = {n._node_uid: set() for n in compute}
        input_consumers: set = set()
        for n in compute:
            for arg in n._all_args():
                if isinstance(arg, ClassMethodNode) and \
                        actor_of[arg._node_uid] != actor_of[n._node_uid]:
                    consumers[arg._node_uid].add(actor_of[n._node_uid])
                elif isinstance(arg, (InputNode, InputAttributeNode)):
                    input_consumers.add(actor_of[n._node_uid])
            # source nodes sync on the input channel for stop/backpressure
            if not any(isinstance(a, DAGNode) for a in n._all_args()):
                input_consumers.add(actor_of[n._node_uid])

        out_uids = {o._node_uid for o in self._outputs}

        def make_spec(uid: Optional[int], reader_actors: set,
                      driver_reads: bool) -> ChannelSpec:
            return ChannelSpec(
                channel_id=os.urandom(8),
                num_readers=len(reader_actors) + (1 if driver_reads else 0),
                capacity=buffer_capacity)

        # channel per cross-actor-consumed or terminal node, + input
        self._chan_specs: Dict[int, ChannelSpec] = {}
        reader_order: Dict[int, List] = {}
        for n in compute:
            uid = n._node_uid
            drv = uid in out_uids
            if consumers[uid] or drv:
                self._chan_specs[uid] = make_spec(uid, consumers[uid], drv)
                reader_order[uid] = sorted(consumers[uid],
                                           key=lambda a: a.hex())
        self._input_spec = make_spec(None, input_consumers, False)
        input_reader_order = sorted(input_consumers, key=lambda a: a.hex())

        def reader_idx(uid: Optional[int], actor_id) -> int:
            order = (input_reader_order if uid is None
                     else reader_order[uid])
            return order.index(actor_id)

        # per-actor schedules
        handles: Dict[Any, Any] = {}
        schedules: Dict[Any, dict] = {}
        for n in compute:
            aid = actor_of[n._node_uid]
            handles[aid] = n._handle
            schedules.setdefault(aid, {"reads": {}, "writes": {},
                                       "nodes": [],
                                       "zero_copy": zero_copy_reads})
        for n in compute:
            aid = actor_of[n._node_uid]
            sched = schedules[aid]

            def argspec(arg):
                if isinstance(arg, InputNode):
                    sched["reads"]["__input__"] = (
                        self._input_spec, reader_idx(None, aid))
                    return ("chan", "__input__", None)
                if isinstance(arg, InputAttributeNode):
                    sched["reads"]["__input__"] = (
                        self._input_spec, reader_idx(None, aid))
                    return ("chan", "__input__", arg._selector)
                if isinstance(arg, ClassMethodNode):
                    uid = arg._node_uid
                    if actor_of[uid] == aid:
                        return ("local", uid)
                    key = f"n{uid}"
                    sched["reads"][key] = (self._chan_specs[uid],
                                           reader_idx(uid, aid))
                    return ("chan", key, None)
                if isinstance(arg, DAGNode):
                    raise ValueError(f"unsupported node type {type(arg)}")
                return ("const", arg)

            entry = {
                "uid": n._node_uid,
                "method": n._method_name,
                "args": [argspec(a) for a in n._bound_args],
                "kwargs": {k: argspec(v)
                           for k, v in n._bound_kwargs.items()},
                "sync_input": not any(isinstance(a, DAGNode)
                                      for a in n._all_args()),
            }
            if entry["sync_input"]:
                sched["reads"]["__input__"] = (
                    self._input_spec, reader_idx(None, aid))
            if n._node_uid in self._chan_specs:
                sched["writes"][n._node_uid] = self._chan_specs[n._node_uid]
            sched["nodes"].append(entry)

        # channels are same-arena: every participating actor must sit on
        # the head node (where the driver's endpoints live)
        import time as _time
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        if getattr(rt, "is_driver", False):
            deadline = _time.monotonic() + 10.0
            for aid in handles:
                while True:
                    info = rt.actors.get(aid)
                    if info is not None and info.node_id is not None:
                        break
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"actor {aid} not placed within 10s; cannot "
                            "compile DAG")
                    _time.sleep(0.01)
                if info.node_id != rt.head_node_id:
                    raise ValueError(
                        f"compiled graphs require all actors on the head "
                        f"node (shared shm arena); actor {aid} is on "
                        f"node {info.node_id}")

        # driver-side endpoints
        self._input_writer = ChannelWriter(self._input_spec)
        self._output_readers = [
            ChannelReader(self._chan_specs[o._node_uid],
                          # driver is always the last reader index
                          self._chan_specs[o._node_uid].num_readers - 1)
            for o in self._outputs]
        self._next_seq = 0
        self._torn_down = False

        # install the loops
        self._loop_refs = [
            handles[aid].__ray_call__.remote(_compiled_dag_loop, sched)
            for aid, sched in schedules.items()]

    # ------------------------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        seq = self._next_seq
        self._next_seq += 1
        self._input_writer.write((args, kwargs), seq)
        return CompiledDAGRef(self, seq)

    def _read_output(self, seq: int, timeout: Optional[float]):
        # read everything before acking anything, so a timeout on one
        # output leaves the whole seq re-readable
        raw = [reader.read(seq, timeout)
               for reader in self._output_readers]
        # deep-copy: read values may be zero-copy views into channel
        # slots the writer will reuse after `capacity` more executions
        values = [v if isinstance(v, _ErrorToken) else copy.deepcopy(v)
                  for v in raw]
        for reader in self._output_readers:
            reader.ack(seq)
        errors = [v for v in values if isinstance(v, _ErrorToken)]
        if errors:
            return errors[0]
        return values if self._multi else values[0]

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu
        self._input_writer.write(_STOP, self._next_seq)
        try:
            ray_tpu.get(self._loop_refs, timeout=30.0)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass
