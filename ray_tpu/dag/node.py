"""DAG node types (reference: python/ray/dag/dag_node.py,
input_node.py, class_node.py, function_node.py — nodes built with
`.bind()`, executed lazily or compiled).

Uncompiled execution (`node.execute(...)`) walks the graph and submits
one task per node through the normal runtime. Compiling
(`experimental_compile`) replaces per-call submission with
pre-established shared-memory channels — see ray_tpu/dag/compiled.py.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self._node_uid = next(_node_counter)

    # -- graph walking ---------------------------------------------------
    def upstream(self) -> List["DAGNode"]:
        return [a for a in self._all_args() if isinstance(a, DAGNode)]

    def _all_args(self) -> List[Any]:
        return []

    def topo_sort(self) -> List["DAGNode"]:
        """All ancestors + self, dependencies first, deterministic."""
        order: List[DAGNode] = []
        seen = set()

        def visit(node: DAGNode):
            if node._node_uid in seen:
                return
            seen.add(node._node_uid)
            for up in node.upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # -- uncompiled execution -------------------------------------------
    def execute(self, *args, **kwargs):
        """Submit the whole DAG through the normal task path; returns an
        ObjectRef (or list of refs for MultiOutputNode)."""
        memo: Dict[int, Any] = {}
        return self._eval(memo, args, kwargs)

    def _eval(self, memo, in_args, in_kwargs):
        raise NotImplementedError

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, **kwargs)


def _resolve(arg, memo, in_args, in_kwargs):
    if isinstance(arg, DAGNode):
        return arg._eval(memo, in_args, in_kwargs)
    return arg


class InputNode(DAGNode):
    """The DAG's input placeholder; supports `with InputNode() as inp`.

    `inp` is the single positional arg (or the tuple of them);
    `inp[i]` / `inp.key` select positional / keyword args.
    """

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, index) -> "InputAttributeNode":
        return InputAttributeNode(self, ("idx", index))

    def __getattr__(self, name: str) -> "InputAttributeNode":
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, ("key", name))

    @staticmethod
    def extract(selector: Optional[Tuple[str, Any]], in_args, in_kwargs):
        if selector is None:
            if in_kwargs:
                raise ValueError(
                    "DAG input has keyword args; consume them via "
                    "inp.<name>, not the bare InputNode")
            if len(in_args) != 1:
                return tuple(in_args)
            return in_args[0]
        kind, sel = selector
        return in_args[sel] if kind == "idx" else in_kwargs[sel]

    def _eval(self, memo, in_args, in_kwargs):
        return self.extract(None, in_args, in_kwargs)


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, selector: Tuple[str, Any]):
        super().__init__()
        self._parent = parent
        self._selector = selector

    def _all_args(self):
        return [self._parent]

    def _eval(self, memo, in_args, in_kwargs):
        return InputNode.extract(self._selector, in_args, in_kwargs)


class ClassMethodNode(DAGNode):
    """actor.method.bind(...) — an actor-method call in the DAG."""

    def __init__(self, handle, method_name: str, args: tuple, kwargs: dict):
        super().__init__()
        self._handle = handle
        self._method_name = method_name
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _all_args(self):
        return list(self._bound_args) + list(self._bound_kwargs.values())

    def _eval(self, memo, in_args, in_kwargs):
        if self._node_uid in memo:
            return memo[self._node_uid]
        args = [_resolve(a, memo, in_args, in_kwargs)
                for a in self._bound_args]
        kwargs = {k: _resolve(v, memo, in_args, in_kwargs)
                  for k, v in self._bound_kwargs.items()}
        from ray_tpu.core.actor import ActorMethod
        ref = ActorMethod(self._handle, self._method_name).remote(
            *args, **kwargs)
        memo[self._node_uid] = ref
        return ref


class FunctionNode(DAGNode):
    """fn.bind(...) — a task call in the DAG (uncompiled mode only)."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__()
        self._remote_fn = remote_fn
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _all_args(self):
        return list(self._bound_args) + list(self._bound_kwargs.values())

    def _eval(self, memo, in_args, in_kwargs):
        if self._node_uid in memo:
            return memo[self._node_uid]
        args = [_resolve(a, memo, in_args, in_kwargs)
                for a in self._bound_args]
        kwargs = {k: _resolve(v, memo, in_args, in_kwargs)
                  for k, v in self._bound_kwargs.items()}
        ref = self._remote_fn.remote(*args, **kwargs)
        memo[self._node_uid] = ref
        return ref


class MultiOutputNode(DAGNode):
    """Terminal node returning several leaves (reference:
    python/ray/dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self._outputs = list(outputs)

    def _all_args(self):
        return list(self._outputs)

    def _eval(self, memo, in_args, in_kwargs):
        return [_resolve(o, memo, in_args, in_kwargs)
                for o in self._outputs]
