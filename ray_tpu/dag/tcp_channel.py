"""Cross-node compiled-graph channels over pre-established TCP.

Reference: python/ray/experimental/channel/nccl_group.py:21 — compiled
DAGs move cross-GPU edges over pre-created NCCL P2P channels, no
per-call RPC. The TPU-host analog for cross-NODE edges is a dedicated
worker-to-worker TCP connection per (writer, reader) link, established
once at compile time: frames are length-prefixed serialized values,
and capacity semantics come from a credit loop (the reader returns one
credit byte per consumed item; the writer blocks once ``capacity``
items are unacknowledged — the same bounded-buffer backpressure the
shm ring gives co-located actors).

Interface-compatible with dag.channel.ChannelWriter/ChannelReader
(write(value, seq) / read(seq) / ack(seq)): TCP ordering makes the
seq implicit, and the compiled loop consumes strictly sequentially.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

from ray_tpu.devtools import locktrace
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.dag.channel import ChannelTimeoutError

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        try:
            chunk = sock.recv(n)
        except OSError:
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class TcpChannelListener:
    """Reader-side endpoint, created BEFORE the writer connects.

    One listener per (channel, reader); accept() runs lazily on first
    read so install order can't deadlock."""

    def __init__(self, host: Optional[str] = None):
        import os
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(1)
        if host is None:
            # prefer the node's advertised address (daemons export it
            # to their workers): gethostbyname(gethostname()) resolves
            # to 127.0.1.1 on stock Debian /etc/hosts, unreachable from
            # other physical hosts
            host = (os.environ.get("RTPU_NODE_ADVERTISE_HOST")
                    or socket.gethostbyname(socket.gethostname()))
        self.address: Tuple[str, int] = (host,
                                         self._sock.getsockname()[1])
        self._conn: Optional[socket.socket] = None
        self._lock = locktrace.traced_lock("dag.tcp_channel")

    def _ensure_accepted(self, timeout: Optional[float]) -> socket.socket:
        # accept() can block for the full timeout — do it OUTSIDE the
        # lock so close() (and locktrace) never stall behind a reader
        # waiting for a writer that hasn't connected yet
        with self._lock:
            if self._conn is not None:
                return self._conn
            listening = self._sock
        listening.settimeout(timeout)
        try:
            conn, _ = listening.accept()
        except (socket.timeout, OSError):
            raise ChannelTimeoutError(
                "tcp channel writer never connected")
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._lock:
            if self._conn is None:
                self._conn = conn
                return conn
        # lost the (single-writer, so improbable) accept race: keep the
        # established connection, drop ours
        try:
            conn.close()
        except OSError:
            logger.debug("stray accepted connection close failed",
                         exc_info=True)
        with self._lock:
            return self._conn

    def close(self) -> None:
        with self._lock:
            for s in (self._conn, self._sock):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
            self._conn = None


class TcpChannelReader:
    """read(seq)/ack(seq) over the accepted connection.

    Frames arrive in the writer's seq order; a seq-indexed buffer makes
    reads ADDRESSABLE like the shm ring: out-of-order ``get()``s return
    the right execution's value, and a timed-out read leaves the seq
    re-readable (incoming bytes accumulate across calls — a partial
    frame is never lost to a timeout). ``ack`` drops the buffered value
    and returns one credit."""

    owned_reads = True  # deserialization yields owned objects: the
    # compiled loop may skip its defensive copy

    def __init__(self, listener: TcpChannelListener):
        self._listener = listener
        self._rx = bytearray()
        self._values: Dict[int, Any] = {}
        self._next_seq = 0

    def _pump(self, conn, timeout: Optional[float]) -> bool:
        """Receive once, parse any completed frames; False on timeout."""
        conn.settimeout(timeout)
        try:
            chunk = conn.recv(1 << 20)
        except socket.timeout:
            return False
        except OSError:
            raise ChannelTimeoutError("tcp channel connection lost")
        if not chunk:
            raise ChannelTimeoutError("tcp channel writer closed")
        self._rx += chunk
        while len(self._rx) >= _LEN.size:
            (length,) = _LEN.unpack_from(self._rx)
            end = _LEN.size + length
            if len(self._rx) < end:
                break
            payload = bytes(self._rx[_LEN.size:end])
            del self._rx[:end]
            self._values[self._next_seq] = serialization.loads(payload)
            self._next_seq += 1
        return True

    def read(self, seq: int, timeout: Optional[float] = 60.0) -> Any:
        import time as _time
        conn = self._listener._ensure_accepted(timeout)
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        while seq not in self._values:
            remaining = (None if deadline is None
                         else deadline - _time.monotonic())
            if remaining is not None and remaining <= 0:
                raise ChannelTimeoutError(
                    f"tcp channel read timed out at seq {seq}")
            if not self._pump(conn, remaining):
                raise ChannelTimeoutError(
                    f"tcp channel read timed out at seq {seq}")
        return self._values[seq]

    def ack(self, seq: int) -> None:
        self._values.pop(seq, None)
        conn = self._listener._ensure_accepted(None)
        try:
            conn.sendall(b"\x01")  # one credit back to the writer
        except OSError:
            pass  # writer gone (teardown): nothing to backpressure

    def close(self) -> None:
        self._listener.close()


class TcpChannelWriter:
    """Writer-side fan-out: one connection per remote reader, with a
    per-reader credit window of ``capacity``."""

    def __init__(self, endpoints, capacity: int,
                 connect_timeout: float = 30.0):
        self._conns = []
        self._credits = []
        self._capacity = capacity
        for host, port in endpoints:
            sock = socket.create_connection((host, port),
                                            timeout=connect_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(sock)
            self._credits.append(capacity)

    def write(self, value: Any, seq: int,
              timeout: Optional[float] = 60.0) -> None:
        payload = serialization.dumps(value)
        frame = _LEN.pack(len(payload)) + payload
        for i, conn in enumerate(self._conns):
            # consume acks to refill the credit window; block when empty
            conn.settimeout(timeout)
            while self._credits[i] <= 0:
                try:
                    acks = conn.recv(4096)
                except socket.timeout:
                    raise ChannelTimeoutError(
                        f"tcp channel writer blocked at seq {seq}: "
                        f"reader {i} not consuming")
                except OSError:
                    raise ChannelTimeoutError(
                        f"tcp channel reader {i} disconnected")
                if not acks:
                    raise ChannelTimeoutError(
                        f"tcp channel reader {i} closed")
                self._credits[i] += len(acks)
            # drain any queued acks opportunistically (non-blocking)
            conn.setblocking(False)
            try:
                acks = conn.recv(4096)
                if acks:
                    self._credits[i] += len(acks)
            except (BlockingIOError, OSError):
                pass
            conn.setblocking(True)
            conn.settimeout(timeout)
            try:
                conn.sendall(frame)
            except OSError:
                raise ChannelTimeoutError(
                    f"tcp channel send failed to reader {i}")
            self._credits[i] -= 1

    def close(self) -> None:
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass


# process-global registry: listeners created during the pre-install
# step, adopted by the compiled loop when it starts (both run in the
# same actor process via __ray_call__)
_listener_registry: Dict[str, TcpChannelListener] = {}
_registry_lock = locktrace.traced_lock("dag.tcp_channel.registry")


def create_listener(token: str) -> Tuple[str, int]:
    """Called on the reader's actor via __ray_call__ before install."""
    listener = TcpChannelListener()
    with _registry_lock:
        _listener_registry[token] = listener
    return listener.address


def adopt_listener(token: str) -> TcpChannelReader:
    with _registry_lock:
        listener = _listener_registry.pop(token)
    return TcpChannelReader(listener)
