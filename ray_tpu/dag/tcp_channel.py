"""Cross-node compiled-graph channels over the shared IO loop.

Reference: python/ray/experimental/channel/nccl_group.py:21 — compiled
DAGs move cross-GPU edges over pre-created NCCL P2P channels, no
per-call RPC. The TPU-host analog for cross-NODE edges is a dedicated
worker-to-worker TCP connection per (writer, reader) link, established
once at compile time. Frames are length-prefixed serialized values and
capacity semantics come from a credit loop (the reader returns one
credit frame per consumed item; the writer blocks once ``capacity``
items are unacknowledged — the same bounded-buffer backpressure the
shm ring gives co-located actors).

Both directions ride ``core.io_loop``: the reader's accepted socket
and every writer connection are registered with the process IO loop,
whose per-connection codec (native wire.cc, or the pure-Python
FrameReader fallback when the C toolchain is absent or
``RAY_TPU_NATIVE_WIRE=0``) parses frames on the loop thread and pushes
them into the channel's seq-indexed buffer. Blocking stays in the
CALLER (``read``/``write`` wait on a Condition); no per-connection
reader thread exists, so an N-channel pipeline keeps the process
thread topology O(1).

Inbound frames are decoded by hand rather than via
``register_message_conn``: a frame that fails to deserialize must
poison the channel (seq assignment is positional — skipping a frame
would silently shift every later value), not be logged and dropped.

Interface-compatible with dag.channel.ChannelWriter/ChannelReader
(write(value, seq) / read(seq) / ack(seq)): TCP ordering makes the
seq implicit, and the compiled loop consumes strictly sequentially.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.io_loop import get_io_loop
from ray_tpu.dag.channel import ChannelTimeoutError
from ray_tpu.devtools import locktrace

logger = logging.getLogger(__name__)

_CREDIT = b"\x01"  # one credit frame, reader -> writer, per ack


class TcpChannelListener:
    """Reader-side endpoint, created BEFORE the writer connects.

    One listener per (channel, reader). The bound socket is registered
    with the IO loop immediately, so the writer's connect is accepted
    (and its frames buffered) even if the reader hasn't issued a read
    yet — install order can't deadlock. The listener owns all receive
    state; TcpChannelReader is a thin view over it, which lets
    ``create_listener``/``adopt_listener`` split endpoint creation from
    reader construction across __ray_call__ steps."""

    def __init__(self, host: Optional[str] = None):
        import os
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(1)
        if host is None:
            # prefer the node's advertised address (daemons export it
            # to their workers): gethostbyname(gethostname()) resolves
            # to 127.0.1.1 on stock Debian /etc/hosts, unreachable from
            # other physical hosts
            host = (os.environ.get("RTPU_NODE_ADVERTISE_HOST")
                    or socket.gethostbyname(socket.gethostname()))
        self.address: Tuple[str, int] = (host,
                                         self._sock.getsockname()[1])
        self._cond = threading.Condition()
        self._values: Dict[int, Any] = {}
        self._next_seq = 0
        self._conn = None  # LoopConnection once the writer connects
        self._error: Optional[str] = None
        self._closed = False
        self._loop_listener = get_io_loop().register_listener(
            self._sock, self._on_accept,
            label=f"dag.tcp_channel:{self.address[1]}")

    # -------------------------------------------- loop-thread handlers

    def _on_accept(self, sock: socket.socket, addr) -> None:
        with self._cond:
            stale = self._closed or self._conn is not None
        if stale:
            # single-writer channel: drop stray connections
            try:
                sock.close()
            except OSError:
                pass
            return
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = get_io_loop().register(
            sock, self._on_frames, self._on_close,
            label=f"dag.tcp_channel.reader:{self.address[1]}")
        with self._cond:
            self._conn = conn
            self._cond.notify_all()

    def _on_frames(self, conn, frames) -> None:
        with self._cond:
            for frame in frames:
                try:
                    value = serialization.loads(frame)
                except Exception:
                    logger.exception(
                        "tcp channel: undecodable frame at seq %d",
                        self._next_seq)
                    self._error = (f"tcp channel frame decode failed at "
                                   f"seq {self._next_seq}")
                    break
                self._values[self._next_seq] = value
                self._next_seq += 1
            self._cond.notify_all()

    def _on_close(self, conn) -> None:
        with self._cond:
            if self._error is None and not self._closed:
                self._error = "tcp channel writer closed"
            self._cond.notify_all()

    # ------------------------------------------------------ caller API

    def _read(self, seq: int, timeout: Optional[float]) -> Any:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            # values buffered before EOF/teardown stay readable: only
            # consult the error state when the seq hasn't arrived
            while seq not in self._values:
                if self._error is not None:
                    raise ChannelTimeoutError(self._error)
                if self._closed:
                    raise ChannelTimeoutError("tcp channel reader closed")
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise ChannelTimeoutError(
                        f"tcp channel read timed out at seq {seq}")
                self._cond.wait(remaining)
            return self._values[seq]

    def _ack(self, seq: int) -> None:
        with self._cond:
            self._values.pop(seq, None)
            conn = self._conn
        if conn is None or conn.closed:
            return  # writer gone (teardown): nothing to backpressure
        try:
            conn.send_frame(_CREDIT)
        except OSError:
            pass

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            conn = self._conn
            self._cond.notify_all()
        if conn is not None:
            conn.close()
        self._loop_listener.close(wait=False)


class TcpChannelReader:
    """read(seq)/ack(seq) over the accepted connection.

    Frames arrive in the writer's seq order; the listener's seq-indexed
    buffer makes reads ADDRESSABLE like the shm ring: out-of-order
    ``get()``s return the right execution's value, and a timed-out read
    leaves the seq re-readable (the loop keeps delivering frames while
    the caller is away). ``ack`` drops the buffered value and returns
    one credit frame."""

    owned_reads = True  # deserialization yields owned objects: the
    # compiled loop may skip its defensive copy

    def __init__(self, listener: TcpChannelListener):
        self._listener = listener

    def read(self, seq: int, timeout: Optional[float] = 60.0) -> Any:
        return self._listener._read(seq, timeout)

    def ack(self, seq: int) -> None:
        self._listener._ack(seq)

    def close(self) -> None:
        self._listener.close()


class _WriterLink:
    """One writer->reader connection plus its credit window. Credits
    are incremented by the loop thread (one per inbound frame) and
    consumed by ``write`` under the shared writer Condition."""

    __slots__ = ("conn", "credits", "closed")

    def __init__(self, capacity: int):
        self.conn = None
        self.credits = capacity
        self.closed = False


class TcpChannelWriter:
    """Writer-side fan-out: one loop-registered connection per remote
    reader, with a per-reader credit window of ``capacity``."""

    def __init__(self, endpoints, capacity: int,
                 connect_timeout: float = 30.0):
        self._capacity = capacity
        self._cond = threading.Condition()
        self._links: List[_WriterLink] = []
        loop = get_io_loop()
        for i, (host, port) in enumerate(endpoints):
            sock = socket.create_connection((host, port),
                                            timeout=connect_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            link = _WriterLink(capacity)
            link.conn = loop.register(
                sock, self._on_credits(link), self._on_close(link),
                label=f"dag.tcp_channel.writer:{host}:{port}")
            self._links.append(link)

    def _on_credits(self, link: _WriterLink):
        def handler(conn, frames):
            with self._cond:
                link.credits += len(frames)
                self._cond.notify_all()
        return handler

    def _on_close(self, link: _WriterLink):
        def handler(conn):
            with self._cond:
                link.closed = True
                self._cond.notify_all()
        return handler

    def write(self, value: Any, seq: int,
              timeout: Optional[float] = 60.0) -> None:
        payload = serialization.dumps(value)
        for i, link in enumerate(self._links):
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            with self._cond:
                # block until the reader returns a credit; a dead link
                # must error promptly, not run out the timeout
                while link.credits <= 0:
                    if link.closed:
                        raise ChannelTimeoutError(
                            f"tcp channel reader {i} closed")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise ChannelTimeoutError(
                            f"tcp channel writer blocked at seq {seq}: "
                            f"reader {i} not consuming")
                    self._cond.wait(remaining)
                if link.closed:
                    raise ChannelTimeoutError(
                        f"tcp channel reader {i} closed")
                link.credits -= 1
            try:
                link.conn.send_frame(payload)
            except OSError:
                raise ChannelTimeoutError(
                    f"tcp channel send failed to reader {i}")

    def close(self) -> None:
        for link in self._links:
            if link.conn is not None:
                link.conn.close()


# process-global registry: listeners created during the pre-install
# step, adopted by the compiled loop when it starts (both run in the
# same actor process via __ray_call__)
_listener_registry: Dict[str, TcpChannelListener] = {}
_registry_lock = locktrace.traced_lock("dag.tcp_channel.registry")


def create_listener(token: str) -> Tuple[str, int]:
    """Called on the reader's actor via __ray_call__ before install."""
    listener = TcpChannelListener()
    with _registry_lock:
        _listener_registry[token] = listener
    return listener.address


def adopt_listener(token: str) -> TcpChannelReader:
    with _registry_lock:
        listener = _listener_registry.pop(token)
    return TcpChannelReader(listener)
