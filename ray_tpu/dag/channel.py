"""Shared-memory channels for compiled graphs.

Reference: python/ray/experimental/channel/shared_memory_channel.py:151
(Channel over mutable plasma objects, reader-acked, bounded buffering).
Here a channel is a sliding window of `capacity` sealed objects in the
node's shm arena, addressed by (channel_id, seq): the writer seals
`seq`, each reader polls the arena directly (no control-plane RPC on
the data path) and deposits a tiny ack object; the writer reclaims slot
`seq - capacity` only after every reader acked it, which is also the
backpressure bound on in-flight executions.

Same-store only: writer and all readers must share one shm arena (the
same node). Values read out may be zero-copy views into the arena; they
stay valid for at least `capacity - 1` further writes.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Any, List, Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError

_POLL_S = 0.0002


class ChannelTimeoutError(GetTimeoutError):
    pass


@dataclass(frozen=True)
class ChannelSpec:
    """Picklable channel identity; bind to a store on each side."""

    channel_id: bytes  # 8 random bytes
    num_readers: int
    capacity: int = 4

    def data_oid(self, seq: int) -> ObjectID:
        h = hashlib.sha1(b"chan:" + self.channel_id
                         + seq.to_bytes(8, "little")).digest()
        return ObjectID(h[: ObjectID.SIZE])

    def ack_oid(self, seq: int, reader: int) -> ObjectID:
        h = hashlib.sha1(b"chack:" + self.channel_id
                         + seq.to_bytes(8, "little")
                         + reader.to_bytes(2, "little")).digest()
        return ObjectID(h[: ObjectID.SIZE])


def _local_store():
    """The shm arena of the node this process lives on."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    if getattr(rt, "is_driver", False):
        return rt.nodes[rt.head_node_id].store
    return rt.store


class ChannelWriter:
    def __init__(self, spec: ChannelSpec, store=None):
        self.spec = spec
        self.store = store or _local_store()

    def write(self, value: Any, seq: int,
              timeout: Optional[float] = 60.0) -> None:
        spec = self.spec
        if seq >= spec.capacity:
            old = seq - spec.capacity
            deadline = None if timeout is None else (
                time.monotonic() + timeout)
            for reader in range(spec.num_readers):
                ack = spec.ack_oid(old, reader)
                while not self.store.contains(ack):
                    if deadline is not None and time.monotonic() > deadline:
                        raise ChannelTimeoutError(
                            f"channel writer blocked: seq {old} not "
                            f"acked by reader {reader}")
                    time.sleep(_POLL_S)
                self.store.delete(ack)
            self.store.delete(spec.data_oid(old))
        self.store.put_value(spec.data_oid(seq), value)


class ChannelReader:
    def __init__(self, spec: ChannelSpec, reader_idx: int, store=None):
        self.spec = spec
        self.reader_idx = reader_idx
        self.store = store or _local_store()

    def read(self, seq: int, timeout: Optional[float] = 60.0) -> Any:
        found, value = self.store.get_value(
            self.spec.data_oid(seq),
            timeout_s=1e9 if timeout is None else timeout)
        if not found:
            raise ChannelTimeoutError(
                f"channel read timed out at seq {seq}")
        return value

    def ack(self, seq: int) -> None:
        oid = self.spec.ack_oid(seq, self.reader_idx)
        if self.store.contains(oid):
            return  # idempotent: a retried get() may re-ack
        self.store.create(oid, 1)
        self.store.seal(oid)
