"""Job submission (reference: python/ray/dashboard/modules/job/ — the
JobSubmissionClient SDK + job manager that runs entrypoint commands,
tracks status, and serves logs).

Jobs are driver programs: each entrypoint runs as a subprocess with its
own runtime (the reference runs them on the head node the same way).
Status and metadata live in the GCS KV under the "jobs" namespace; logs
stream to a per-job file in the session dir.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobEntry:
    def __init__(self, submission_id: str, entrypoint: str, log_path: str,
                 metadata: Optional[Dict[str, str]]):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.metadata = metadata or {}
        self.status = JobStatus.PENDING
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.proc: Optional[subprocess.Popen] = None
        self.message = ""

    def info(self) -> Dict[str, Any]:
        return {
            "submission_id": self.submission_id,
            "entrypoint": self.entrypoint,
            "status": self.status,
            "message": self.message,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "metadata": self.metadata,
            "log_path": self.log_path,
        }


def list_job_infos(gcs) -> List[Dict[str, Any]]:
    """All submitted-job records from the GCS "jobs" KV namespace — the
    shared table every client and the state API read."""
    out = []
    for key in gcs.kv.keys(namespace="jobs"):
        blob = gcs.kv.get(key, namespace="jobs")
        if blob is not None:  # deleted between keys() and get()
            out.append(json.loads(blob.decode()))
    return out


class JobSubmissionClient:
    """In-process manager + SDK (reference:
    python/ray/dashboard/modules/job/sdk.py JobSubmissionClient)."""

    _singleton: Optional["JobSubmissionClient"] = None
    _singleton_lock = threading.Lock()
    # separate from _singleton_lock: shared() holds that lock while
    # calling __init__, so reusing it here would deadlock
    _table_lock = threading.Lock()

    def __init__(self, address: Optional[str] = None):
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        if rt is None or not getattr(rt, "is_driver", False):
            raise RuntimeError("JobSubmissionClient needs an initialized "
                               "driver (ray_tpu.init)")
        self._rt = rt
        head = rt.nodes[rt.head_node_id]
        self._log_dir = os.path.join(head.session_dir, "jobs")
        os.makedirs(self._log_dir, exist_ok=True)
        # Process-handle table shared by every client of the same runtime
        # (lives on the runtime so its lifetime tracks the runtime's), so
        # a second JobSubmissionClient() can stop jobs the first submitted.
        # The authoritative *status* table is the GCS "jobs" KV namespace.
        with JobSubmissionClient._table_lock:
            if not hasattr(rt, "_submitted_jobs"):
                rt._submitted_jobs = {}
                rt._submitted_jobs_lock = threading.Lock()
            self._jobs: Dict[str, _JobEntry] = rt._submitted_jobs
            # shared with every client of this runtime so check-and-insert
            # in submit_job is atomic across clients
            self._lock = rt._submitted_jobs_lock

    @classmethod
    def shared(cls) -> "JobSubmissionClient":
        with cls._singleton_lock:
            if cls._singleton is None:
                cls._singleton = cls()
            return cls._singleton

    # ------------------------------------------------------------------
    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        log_path = os.path.join(self._log_dir, f"{submission_id}.log")
        entry = _JobEntry(submission_id, entrypoint, log_path, metadata)
        with self._lock:
            if submission_id in self._jobs:
                raise ValueError(f"job {submission_id!r} already exists")
            self._jobs[submission_id] = entry
        self._publish(entry)

        env = dict(os.environ)
        for key, value in (runtime_env or {}).get("env_vars", {}).items():
            env[key] = str(value)
        if runtime_env and "working_dir" in runtime_env:
            cwd = runtime_env["working_dir"]
        else:
            cwd = None

        def run():
            with open(log_path, "wb") as log:
                try:
                    entry.proc = subprocess.Popen(
                        entrypoint, shell=True, stdout=log,
                        stderr=subprocess.STDOUT, env=env, cwd=cwd)
                    entry.status = JobStatus.RUNNING
                    self._publish(entry)
                    code = entry.proc.wait()
                    if entry.status == JobStatus.STOPPED:
                        pass
                    elif code == 0:
                        entry.status = JobStatus.SUCCEEDED
                    else:
                        entry.status = JobStatus.FAILED
                        entry.message = f"exit code {code}"
                except Exception as e:  # noqa: BLE001
                    entry.status = JobStatus.FAILED
                    entry.message = repr(e)
            entry.end_time = time.time()
            self._publish(entry)

        threading.Thread(target=run, daemon=True,
                         name=f"job-{submission_id}").start()
        return submission_id

    def _publish(self, entry: _JobEntry) -> None:
        self._rt.gcs.kv.put(entry.submission_id.encode(),
                            json.dumps(entry.info()).encode(),
                            namespace="jobs")

    def _entry(self, submission_id: str) -> Optional[_JobEntry]:
        with self._lock:
            return self._jobs.get(submission_id)

    def _kv_info(self, submission_id: str) -> Dict[str, Any]:
        """The shared job table is the GCS "jobs" KV namespace — every
        client (and the state API/CLI) reads the same records, whichever
        client instance submitted the job."""
        blob = self._rt.gcs.kv.get(submission_id.encode(), namespace="jobs")
        if blob is None:
            raise ValueError(f"no job {submission_id!r}")
        return json.loads(blob.decode())

    def get_job_status(self, submission_id: str) -> str:
        return self._kv_info(submission_id)["status"]

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return self._kv_info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        log_path = self._kv_info(submission_id).get("log_path", "")
        try:
            with open(log_path, "rb") as f:
                return f.read().decode("utf-8", "replace")
        except (FileNotFoundError, IsADirectoryError):
            return ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        return list_job_infos(self._rt.gcs)

    def stop_job(self, submission_id: str) -> bool:
        entry = self._entry(submission_id)
        if entry is None:
            self._kv_info(submission_id)  # raises if the job is unknown
            return False
        if entry.proc is not None and entry.proc.poll() is None:
            entry.status = JobStatus.STOPPED
            entry.proc.terminate()
            try:
                entry.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                entry.proc.kill()
            self._publish(entry)
            return True
        return False

    def wait_until_finish(self, submission_id: str,
                          timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        terminal = {JobStatus.SUCCEEDED, JobStatus.FAILED,
                    JobStatus.STOPPED}
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in terminal:
                return status
            time.sleep(0.05)
        raise TimeoutError(f"job {submission_id} still "
                           f"{self.get_job_status(submission_id)}")
