"""ray_tpu.data — streaming, lazy, distributed datasets over the task
runtime, with Arrow blocks and a TPU device-feed path.

reference: python/ray/data/__init__.py public surface.
"""

from ray_tpu.data.aggregate import (
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Quantile,
    Std,
    Sum,
)
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.expressions import col, lit
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data import preprocessors
from ray_tpu.data.datasource import Datasink, Datasource
from ray_tpu.data.read_api import (
    from_arrow,
    from_blocks,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "AggregateFn", "Block", "BlockAccessor", "BlockMetadata", "Count",
    "DataContext", "DataIterator", "Datasink", "Dataset", "Datasource",
    "GroupedData", "Max", "MaterializedDataset", "Mean", "Min",
    "Quantile", "Std", "Sum", "col", "from_arrow", "from_blocks",
    "from_huggingface", "from_items", "from_numpy", "from_pandas", "from_torch", "lit", "preprocessors",
    "range", "range_tensor", "read_binary_files", "read_csv",
    "read_datasource", "read_images", "read_json", "read_numpy",
    "read_parquet", "read_sql", "read_text", "read_tfrecords",
    "read_webdataset",
]
