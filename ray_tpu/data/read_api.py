"""Dataset creation API: range/from_*/read_* constructors.

reference: python/ray/data/read_api.py (range:?, from_items, from_pandas,
from_numpy, from_arrow, read_parquet, read_csv, read_json).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import make_file_read_tasks, make_range_read_tasks


def _ds(op: L.LogicalOp) -> Dataset:
    return Dataset(L.LogicalPlan(op))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    ctx = DataContext.get_current()
    par = parallelism if parallelism > 0 else min(ctx.min_parallelism, max(n, 1))
    return _ds(L.Read(make_range_read_tasks(n, par), name=f"Range[{n}]"))


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    par = parallelism if parallelism > 0 else min(ctx.min_parallelism, max(n, 1))
    return _ds(L.Read(make_range_read_tasks(n, par, tensor_shape=tuple(shape)),
                      name=f"RangeTensor[{n}]"))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    par = parallelism if parallelism > 0 else min(
        ctx.min_parallelism, max(len(items), 1))
    par = max(1, min(par, len(items) or 1))
    chunks = np.array_split(np.arange(len(items)), par)
    refs, metas = [], []
    for chunk in chunks:
        if len(chunk) == 0:
            continue
        block = BlockAccessor.from_rows([items[i] for i in chunk])
        refs.append(ray_tpu.put(block))
        metas.append(BlockAccessor(block).metadata())
    return _ds(L.InputData(refs, metas))


def from_blocks(blocks: List[pa.Table]) -> Dataset:
    refs = [ray_tpu.put(b) for b in blocks]
    metas = [BlockAccessor(b).metadata() for b in blocks]
    return _ds(L.InputData(refs, metas))


def from_arrow(tables) -> Dataset:
    if isinstance(tables, pa.Table):
        tables = [tables]
    return from_blocks(list(tables))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks([pa.Table.from_pandas(df, preserve_index=False)
                        for df in dfs])


def from_numpy(arrays) -> Dataset:
    from ray_tpu.data.block import stacked_tensor_column
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    blocks = []
    for arr in arrays:
        if arr.ndim == 1:
            blocks.append(pa.table({"data": pa.array(arr)}))
        else:
            blocks.append(pa.table(
                {"data": stacked_tensor_column(arr)}))
    return from_blocks(blocks)


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    from ray_tpu.data.datasource import expand_paths
    files = expand_paths(paths)
    return _ds(L.Read(make_file_read_tasks(files, "parquet", columns, expanded=True),
                      name="ReadParquet", input_files=files))


def read_csv(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    from ray_tpu.data.datasource import expand_paths
    files = expand_paths(paths)
    return _ds(L.Read(make_file_read_tasks(files, "csv", columns, expanded=True),
                      name="ReadCSV", input_files=files))


def read_json(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    from ray_tpu.data.datasource import expand_paths
    files = expand_paths(paths)
    return _ds(L.Read(make_file_read_tasks(files, "json", columns, expanded=True),
                      name="ReadJSON", input_files=files))


def read_text(paths) -> Dataset:
    """One row per line, column "text" (reference: read_api.py
    read_text)."""
    from ray_tpu.data.datasource import _TextRead, expand_paths
    files = expand_paths(paths)
    return _ds(L.Read([_TextRead(p) for p in files],
                      name="ReadText", input_files=files))


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """One row per file, column "bytes" (reference: read_api.py
    read_binary_files)."""
    from ray_tpu.data.datasource import _BinaryRead, expand_paths
    files = expand_paths(paths)
    return _ds(L.Read([_BinaryRead(p, include_paths) for p in files],
                      name="ReadBinary", input_files=files))


def read_images(paths, *, size=None, mode: Optional[str] = None,
                include_paths: bool = False) -> Dataset:
    """Decoded images as HxWxC rows in column "image"; ``size`` is
    (height, width) resize, ``mode`` a PIL mode like "RGB" (reference:
    read_api.py read_images / image_datasource.py)."""
    from ray_tpu.data.datasource import _ImageRead, expand_paths
    files = expand_paths(paths)
    return _ds(L.Read([_ImageRead(p, size, mode, include_paths)
                       for p in files],
                      name="ReadImages", input_files=files))


def read_numpy(paths) -> Dataset:
    """.npy files, rows along axis 0 in column "data" (reference:
    read_api.py read_numpy)."""
    from ray_tpu.data.datasource import _NumpyRead, expand_paths
    return _ds(L.Read([_NumpyRead(p) for p in expand_paths(paths)],
                      name="ReadNumpy"))


def read_datasource(datasource, *, parallelism: int = -1) -> Dataset:
    """Read from a user-defined Datasource (reference: read_api.py:360
    read_datasource over the public Datasource ABC)."""
    from ray_tpu.data.datasource import Datasource
    if not isinstance(datasource, Datasource):
        raise ValueError("read_datasource takes a ray_tpu.data.Datasource")
    ctx = DataContext.get_current()
    par = parallelism if parallelism > 0 else ctx.min_parallelism
    tasks = list(datasource.get_read_tasks(par))
    if not tasks:
        raise ValueError(
            f"{datasource.name}.get_read_tasks returned no read tasks")
    return _ds(L.Read(tasks, name=datasource.name))


def read_tfrecords(paths) -> Dataset:
    """TFRecord files of tf.train.Example protos, one column per
    feature key (reference: read_api.py:2078 read_tfrecords; protobuf
    codec is in-tree — no tensorflow import)."""
    from ray_tpu.data.datasource import TFRecordDatasource
    return read_datasource(TFRecordDatasource(paths))


def read_webdataset(paths, *, decode: bool = True) -> Dataset:
    """WebDataset tar shards: one row per sample key, one column per
    file extension plus "__key__" (reference: read_api.py:2418
    read_webdataset)."""
    from ray_tpu.data.datasource import WebDatasetDatasource
    return read_datasource(WebDatasetDatasource(paths, decode=decode))


def read_sql(sql: str, connection_factory, *,
             shards=None) -> Dataset:
    """DB-API query -> Dataset (reference: read_api.py:2645 read_sql).
    ``shards`` is an optional list of parameter tuples; each runs the
    query as its own read task for parallel partitioned reads."""
    from ray_tpu.data.datasource import SQLDatasource
    return read_datasource(SQLDatasource(sql, connection_factory,
                                         shards=shards))


def from_torch(dataset) -> Dataset:
    """Materialize a torch map- or iterable-style Dataset as rows with
    an "item" column (reference: read_api.py from_torch — same single
    "item" column convention)."""
    import builtins  # this module shadows range() with the Dataset ctor
    try:
        n = len(dataset)
    except TypeError:
        # iterable-style dataset (no __len__)
        items = list(dataset)
    else:
        # map-style: a TypeError from __getitem__ here is a USER bug
        # and must surface from its real call site, not trigger the
        # iterable fallback
        items = [dataset[i] for i in builtins.range(n)]
    return from_items([{"item": it} for it in items])


def from_huggingface(dataset) -> Dataset:
    """A Hugging Face datasets.Dataset -> Dataset (reference:
    read_api.py from_huggingface). Zero-copy when the HF dataset
    exposes its arrow table; falls back to row iteration (covers
    IterableDataset)."""
    data = getattr(dataset, "data", None)
    table = getattr(data, "table", None)
    # HF applies select()/shuffle()/splits through an _indices
    # indirection over the SAME arrow table — zero-copy is only valid
    # when no indirection exists, else it returns the wrong rows
    plain = getattr(dataset, "_indices", None) is None
    if plain and isinstance(table, pa.Table):
        return from_arrow(table.combine_chunks())
    if plain and isinstance(data, pa.Table):
        return from_arrow(data)
    rows = [dict(r) for r in dataset]
    if not rows:
        raise ValueError("cannot construct a Dataset from an empty "
                         "huggingface dataset")
    return from_items(rows)
