"""Datasources and sinks: range/items/files (parquet, csv, json).

reference: python/ray/data/read_api.py and datasource/ — reads become a
list of zero-arg read tasks, one per output block, executed as tasks by
the streaming executor (reference: datasource/datasource.py ReadTask).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import (BlockAccessor, stacked_tensor_column,
                                tensor_column)
from ray_tpu.data.context import DataContext


class _RangeRead:
    def __init__(self, start: int, end: int, tensor_shape=None):
        self.start, self.end, self.tensor_shape = start, end, tensor_shape

    def __call__(self):
        ids = np.arange(self.start, self.end, dtype=np.int64)
        if self.tensor_shape is None:
            return pa.table({"id": pa.array(ids)})
        data = [np.full(self.tensor_shape, i, dtype=np.int64) for i in ids]
        return pa.table({"data": tensor_column(
            data, dtype=np.int64, ndim=len(self.tensor_shape))})


def make_range_read_tasks(n: int, parallelism: int,
                          tensor_shape=None) -> List[Callable]:
    parallelism = max(1, min(parallelism, n) if n else 1)
    per = n // parallelism
    rem = n % parallelism
    tasks, start = [], 0
    for i in range(parallelism):
        size = per + (1 if i < rem else 0)
        tasks.append(_RangeRead(start, start + size, tensor_shape))
        start += size
    return tasks


class _FileRead:
    def __init__(self, path: str, fmt: str, columns=None):
        self.path, self.fmt, self.columns = path, fmt, columns

    def __call__(self):
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            return pq.read_table(self.path, columns=self.columns)
        if self.fmt == "csv":
            import pyarrow.csv as pacsv
            t = pacsv.read_csv(self.path)
            return t.select(self.columns) if self.columns else t
        if self.fmt == "json":
            import pyarrow.json as pajson
            t = pajson.read_json(self.path)
            return t.select(self.columns) if self.columns else t
        raise ValueError(f"unknown format {self.fmt!r}")


class _TextRead:
    """One row per line (reference: read_api.py read_text)."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self):
        with open(self.path, "r", errors="replace") as f:
            lines = f.read().splitlines()
        return pa.table({"text": pa.array(lines, pa.string())})


class _BinaryRead:
    """Whole file as one row (reference: read_binary_files)."""

    def __init__(self, path: str, include_paths: bool = False):
        self.path = path
        self.include_paths = include_paths

    def __call__(self):
        with open(self.path, "rb") as f:
            data = f.read()
        cols = {"bytes": pa.array([data], pa.binary())}
        if self.include_paths:
            cols["path"] = pa.array([self.path], pa.string())
        return pa.table(cols)


class _ImageRead:
    """Decode one image file into an HxWxC uint8 row (reference:
    datasource/image_datasource.py via PIL)."""

    def __init__(self, path: str, size=None, mode: Optional[str] = None,
                 include_paths: bool = False):
        self.path = path
        self.size = size
        self.mode = mode
        self.include_paths = include_paths

    def __call__(self):
        from PIL import Image
        img = Image.open(self.path)
        if self.mode is not None:
            img = img.convert(self.mode)
        if self.size is not None:
            # reference semantics: size=(height, width); PIL takes (w, h)
            img = img.resize((self.size[1], self.size[0]))
        arr = np.asarray(img)
        cols = {"image": tensor_column([arr])}
        if self.include_paths:
            cols["path"] = pa.array([self.path], pa.string())
        return pa.table(cols)


class _NumpyRead:
    """One .npy file -> rows along axis 0 (reference: read_numpy)."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self):
        arr = np.load(self.path)
        if arr.ndim == 1:
            return pa.table({"data": pa.array(arr)})
        return pa.table({"data": stacked_tensor_column(arr)})


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def make_file_read_tasks(paths, fmt: str, columns=None, *,
                         expanded: bool = False) -> List[Callable]:
    """``expanded=True`` means the caller already ran expand_paths —
    re-expanding would re-glob literal filenames containing [?*
    metacharacters and silently drop them."""
    files = paths if expanded else expand_paths(paths)
    return [_FileRead(p, fmt, columns) for p in files]


class _FileWrite:
    """Writes one block to `<dir>/<uuid>-<i>.<ext>` (reference:
    datasource/parquet_datasink.py naming)."""

    def __init__(self, path: str, fmt: str, column=None):
        self.path, self.fmt = path, fmt
        self.column = column

    def __call__(self, block: pa.Table) -> str:
        import uuid
        os.makedirs(self.path, exist_ok=True)
        ext = {"numpy": "npy"}.get(self.fmt, self.fmt)
        name = f"{uuid.uuid4().hex[:12]}.{ext}"
        full = os.path.join(self.path, name)
        if self.fmt == "numpy":
            acc = BlockAccessor(block)
            arrs = acc.to_numpy([self.column] if self.column else None)
            arr = arrs[self.column] if self.column \
                else next(iter(arrs.values()))
            np.save(full, arr)
            return full
        if self.fmt in ("png", "jpeg", "jpg", "bmp"):
            # one image file per row (reference: write_images one file
            # per image, image_datasink.py)
            from PIL import Image
            acc = BlockAccessor(block)
            col = self.column or "image"
            arrs = acc.to_numpy([col])[col]
            stem = uuid.uuid4().hex[:12]
            last = ""  # zero-row block: no file written, say so
            for i, arr in enumerate(arrs):
                last = os.path.join(self.path,
                                    f"{stem}-{i:06d}.{self.fmt}")
                Image.fromarray(np.asarray(arr)).save(last)
            return last
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(block, full)
        elif self.fmt == "csv":
            import pyarrow.csv as pacsv
            pacsv.write_csv(block, full)
        elif self.fmt == "json":
            with open(full, "w") as f:
                import json
                for row in BlockAccessor(block).iter_rows():
                    f.write(json.dumps(_jsonable(row)) + "\n")
        else:
            raise ValueError(f"unknown format {self.fmt!r}")
        return full


def _jsonable(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


# ---------------------------------------------------------------------------
# Public Datasource / Datasink seam
# (reference: python/ray/data/datasource/datasource.py:32 Datasource ABC
#  + read_api.py:360 read_datasource — user-pluggable sources)
# ---------------------------------------------------------------------------


class Datasource:
    """User-pluggable read source: subclass, implement
    ``get_read_tasks``, hand to ``ray_tpu.data.read_datasource``.

    Each read task is a ZERO-ARG callable returning one pyarrow Table
    block; tasks execute as ray_tpu tasks under the streaming executor,
    so they must be picklable and self-contained."""

    def get_read_tasks(self, parallelism: int) -> List[Callable]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        """Optional size hint for the executor's memory budget."""
        return None

    @property
    def name(self) -> str:
        return type(self).__name__


class Datasink:
    """User-pluggable write sink (reference:
    datasource/datasink.py): ``write`` runs once per block as a task
    (must be picklable); ``on_write_complete`` runs on the driver with
    the per-block results (strings come back verbatim, other results
    as 1)."""

    def write(self, block: "pa.Table") -> Any:
        raise NotImplementedError

    def on_write_complete(self, write_results: List[Any]) -> None:
        pass


# ---------------------------------------------------------------------------
# TFRecord (reference: read_api.py:2078 read_tfrecords /
# datasource/tfrecords_datasource.py — here without a tensorflow
# dependency: in-tree tf.train.Example protobuf codec + crc32c framing)
# ---------------------------------------------------------------------------

_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), table-driven — TFRecord framing checksums."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    rotated = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rotated + 0xA282EAD8) & 0xFFFFFFFF


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, i: int):
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _encode_feature(values) -> bytes:
    """tf.train.Feature: 1=BytesList 2=FloatList 3=Int64List."""
    import struct
    body = bytearray()
    if all(isinstance(v, (bytes, str)) for v in values):
        inner = bytearray()
        for v in values:
            b = v.encode() if isinstance(v, str) else v
            inner.append(0x0A)  # field 1, wire 2
            _write_varint(inner, len(b))
            inner += b
        tag = 0x0A  # Feature field 1 (BytesList), wire 2
    elif all(isinstance(v, (int, np.integer)) for v in values):
        inner = bytearray([0x0A])  # Int64List field 1 packed, wire 2
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        _write_varint(inner, len(packed))
        inner += packed
        tag = 0x1A  # Feature field 3, wire 2
    else:
        inner = bytearray([0x0A])  # FloatList field 1 packed, wire 2
        packed = b"".join(struct.pack("<f", float(v)) for v in values)
        _write_varint(inner, len(packed))
        inner += packed
        tag = 0x12  # Feature field 2, wire 2
    body.append(tag)
    _write_varint(body, len(inner))
    body += inner
    return bytes(body)


def _decode_feature(buf: bytes):
    """-> list of bytes/float/int from one Feature message."""
    import struct
    i = 0
    out: List[Any] = []
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire != 2:
            raise ValueError(f"unexpected wire type {wire} in Feature")
        ln, i = _read_varint(buf, i)
        inner = buf[i:i + ln]
        i += ln
        j = 0
        while j < len(inner):
            itag, j = _read_varint(inner, j)
            ifield, iwire = itag >> 3, itag & 7
            if field == 1:  # BytesList
                bln, j = _read_varint(inner, j)
                out.append(bytes(inner[j:j + bln]))
                j += bln
            elif field == 2:  # FloatList
                if iwire == 2:  # packed
                    bln, j = _read_varint(inner, j)
                    out.extend(struct.unpack(
                        f"<{bln // 4}f", inner[j:j + bln]))
                    j += bln
                else:  # fixed32
                    out.append(struct.unpack("<f", inner[j:j + 4])[0])
                    j += 4
            elif field == 3:  # Int64List
                if iwire == 2:  # packed varints
                    bln, j = _read_varint(inner, j)
                    end = j + bln
                    while j < end:
                        v, j = _read_varint(inner, j)
                        out.append(v - (1 << 64) if v >= 1 << 63 else v)
                else:
                    v, j = _read_varint(inner, j)
                    out.append(v - (1 << 64) if v >= 1 << 63 else v)
            else:
                raise ValueError(f"unknown Feature field {field}")
    return out


def encode_example(features: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example."""
    feats = bytearray()
    for key, values in features.items():
        if isinstance(values, (bytes, str, int, float, np.generic)):
            values = [values]
        elif isinstance(values, np.ndarray):
            values = values.tolist()
        kb = key.encode()
        entry = bytearray([0x0A])  # map key, field 1
        _write_varint(entry, len(kb))
        entry += kb
        fv = _encode_feature(list(values))
        entry.append(0x12)  # map value (Feature), field 2
        _write_varint(entry, len(fv))
        entry += fv
        feats.append(0x0A)  # Features.feature map entry, field 1
        _write_varint(feats, len(entry))
        feats += entry
    ex = bytearray([0x0A])  # Example.features, field 1
    _write_varint(ex, len(feats))
    ex += feats
    return bytes(ex)


def decode_example(buf: bytes) -> Dict[str, List[Any]]:
    """serialized tf.train.Example -> {key: [values]}."""
    i = 0
    out: Dict[str, List[Any]] = {}
    tag, i = _read_varint(buf, i)
    if tag >> 3 != 1:
        raise ValueError("not an Example message")
    ln, i = _read_varint(buf, i)
    feats = buf[i:i + ln]
    i = 0
    while i < len(feats):
        tag, i = _read_varint(feats, i)
        if tag >> 3 != 1 or tag & 7 != 2:
            raise ValueError("bad Features map entry")
        ln, i = _read_varint(feats, i)
        entry = feats[i:i + ln]
        i += ln
        j = 0
        key = None
        values: List[Any] = []
        while j < len(entry):
            etag, j = _read_varint(entry, j)
            eln, j = _read_varint(entry, j)
            payload = entry[j:j + eln]
            j += eln
            if etag >> 3 == 1:
                key = payload.decode()
            else:
                values = _decode_feature(payload)
        if key is not None:
            out[key] = values
    return out


def read_tfrecord_file(path: str) -> List[bytes]:
    """Parse TFRecord framing: (len u64le, crc, data, crc) records."""
    import struct
    records = []
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack("<Q", header[:8])
            (lcrc,) = struct.unpack("<I", header[8:])
            if lcrc != _masked_crc(header[:8]):
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            records.append(data)
    return records


class _TFRecordRead:
    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> pa.Table:
        rows = [decode_example(r) for r in read_tfrecord_file(self.path)]
        if not rows:
            return pa.table({})
        # union of feature keys across ALL records (first-record-only
        # would silently drop late-appearing features); a record
        # missing a key yields null in that column
        keys = {}
        for r in rows:
            for k in r:
                keys[k] = True
        cols = {}
        for k in keys:
            vals = [r.get(k) for r in rows]
            if all(v is None or len(v) == 1 for v in vals):
                cols[k] = pa.array(
                    [v[0] if v else None for v in vals])
            else:
                cols[k] = pa.array(
                    [list(v) if v is not None else None for v in vals])
        return pa.table(cols)


class TFRecordDatasource(Datasource):
    """TFRecord files of tf.train.Example protos — the classic TPU
    ingest format, parsed in-tree (no tensorflow import)."""

    def __init__(self, paths):
        self.paths = expand_paths(paths)

    def get_read_tasks(self, parallelism: int) -> List[Callable]:
        return [_TFRecordRead(p) for p in self.paths]

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return sum(os.path.getsize(p) for p in self.paths)


class TFRecordDatasink(Datasink):
    """One .tfrecords file per block under ``path``."""

    def __init__(self, path: str):
        self.path = path

    def write(self, block: pa.Table) -> str:
        import struct
        import uuid
        os.makedirs(self.path, exist_ok=True)
        full = os.path.join(self.path, f"{uuid.uuid4().hex[:12]}.tfrecords")
        acc = BlockAccessor(block)
        with open(full, "wb") as f:
            for row in acc.iter_rows():
                data = encode_example(row)
                header = struct.pack("<Q", len(data))
                f.write(header)
                f.write(struct.pack("<I", _masked_crc(header)))
                f.write(data)
                f.write(struct.pack("<I", _masked_crc(data)))
        return full


# ---------------------------------------------------------------------------
# WebDataset (reference: read_api.py:2418 read_webdataset /
# datasource/webdataset_datasource.py — tar shards, samples grouped by
# basename, one column per extension)
# ---------------------------------------------------------------------------


class _WebDatasetRead:
    def __init__(self, path: str, decode: bool = True):
        self.path = path
        self.decode = decode

    def _decode_entry(self, ext: str, data: bytes):
        if not self.decode:
            return data
        if ext in ("txt", "text"):
            return data.decode("utf-8", "replace")
        if ext == "cls":
            return int(data.decode().strip())
        if ext == "json":
            import json
            return json.loads(data)
        if ext in ("jpg", "jpeg", "png", "bmp"):
            import io
            from PIL import Image
            return np.asarray(Image.open(io.BytesIO(data)))
        if ext == "npy":
            import io
            return np.load(io.BytesIO(data))
        return data

    def __call__(self) -> pa.Table:
        import tarfile
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(self.path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                dirpart, base = os.path.split(member.name)
                if "." not in base:
                    continue
                # webdataset sample key = full path up to the FIRST dot
                # of the basename — same-named files in different tar
                # subdirectories are distinct samples
                stem, ext = base.split(".", 1)
                key = f"{dirpart}/{stem}" if dirpart else stem
                data = tar.extractfile(member).read()
                if key not in samples:
                    samples[key] = {}
                    order.append(key)
                samples[key][ext] = self._decode_entry(ext.lower(), data)
        rows = []
        for key in order:
            row = {"__key__": key}
            row.update(samples[key])
            rows.append(row)
        if not rows:
            return pa.table({"__key__": pa.array([], pa.string())})
        return BlockAccessor.from_rows(rows)


class WebDatasetDatasource(Datasource):
    """WebDataset tar shards: one read task per shard, one row per
    sample key, one column per extension (txt/cls/json/images/npy
    decoded; everything else raw bytes)."""

    def __init__(self, paths, *, decode: bool = True):
        self.paths = expand_paths(paths)
        self.decode = decode

    def get_read_tasks(self, parallelism: int) -> List[Callable]:
        return [_WebDatasetRead(p, self.decode) for p in self.paths]

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return sum(os.path.getsize(p) for p in self.paths)


# ---------------------------------------------------------------------------
# SQL (reference: read_api.py:2645 read_sql / datasource/sql_datasource.py
# — DB-API 2.0 connection factory)
# ---------------------------------------------------------------------------


class _SQLRead:
    def __init__(self, sql: str, connection_factory: Callable,
                 params=None):
        self.sql = sql
        self.connection_factory = connection_factory
        self.params = params

    def __call__(self) -> pa.Table:
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(self.sql, self.params or ())
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        cols = {n: pa.array([r[i] for r in rows])
                for i, n in enumerate(names)}
        if not cols:
            return pa.table({})
        return pa.table(cols)


class SQLDatasource(Datasource):
    """One query = one read task; shard with ``shard_keys`` WHERE
    clauses for parallel reads (the DB-API cursor is created inside the
    task, so the factory must be picklable — e.g. a top-level function,
    not a bound connection)."""

    def __init__(self, sql: str, connection_factory: Callable, *,
                 shards: Optional[List[Any]] = None):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shards = shards

    def get_read_tasks(self, parallelism: int) -> List[Callable]:
        if not self.shards:
            return [_SQLRead(self.sql, self.connection_factory)]
        return [_SQLRead(self.sql, self.connection_factory, params)
                for params in self.shards]


class SQLDatasink(Datasink):
    """Per-block executemany of an INSERT statement."""

    def __init__(self, sql: str, connection_factory: Callable):
        self.sql = sql
        self.connection_factory = connection_factory

    def write(self, block: pa.Table) -> int:
        rows = [tuple(row.values())
                for row in BlockAccessor(block).iter_rows()]
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.executemany(self.sql, rows)
            conn.commit()
        finally:
            conn.close()
        return len(rows)
