"""Datasources and sinks: range/items/files (parquet, csv, json).

reference: python/ray/data/read_api.py and datasource/ — reads become a
list of zero-arg read tasks, one per output block, executed as tasks by
the streaming executor (reference: datasource/datasource.py ReadTask).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import (BlockAccessor, stacked_tensor_column,
                                tensor_column)
from ray_tpu.data.context import DataContext


class _RangeRead:
    def __init__(self, start: int, end: int, tensor_shape=None):
        self.start, self.end, self.tensor_shape = start, end, tensor_shape

    def __call__(self):
        ids = np.arange(self.start, self.end, dtype=np.int64)
        if self.tensor_shape is None:
            return pa.table({"id": pa.array(ids)})
        data = [np.full(self.tensor_shape, i, dtype=np.int64) for i in ids]
        return pa.table({"data": tensor_column(
            data, dtype=np.int64, ndim=len(self.tensor_shape))})


def make_range_read_tasks(n: int, parallelism: int,
                          tensor_shape=None) -> List[Callable]:
    parallelism = max(1, min(parallelism, n) if n else 1)
    per = n // parallelism
    rem = n % parallelism
    tasks, start = [], 0
    for i in range(parallelism):
        size = per + (1 if i < rem else 0)
        tasks.append(_RangeRead(start, start + size, tensor_shape))
        start += size
    return tasks


class _FileRead:
    def __init__(self, path: str, fmt: str, columns=None):
        self.path, self.fmt, self.columns = path, fmt, columns

    def __call__(self):
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            return pq.read_table(self.path, columns=self.columns)
        if self.fmt == "csv":
            import pyarrow.csv as pacsv
            t = pacsv.read_csv(self.path)
            return t.select(self.columns) if self.columns else t
        if self.fmt == "json":
            import pyarrow.json as pajson
            t = pajson.read_json(self.path)
            return t.select(self.columns) if self.columns else t
        raise ValueError(f"unknown format {self.fmt!r}")


class _TextRead:
    """One row per line (reference: read_api.py read_text)."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self):
        with open(self.path, "r", errors="replace") as f:
            lines = f.read().splitlines()
        return pa.table({"text": pa.array(lines, pa.string())})


class _BinaryRead:
    """Whole file as one row (reference: read_binary_files)."""

    def __init__(self, path: str, include_paths: bool = False):
        self.path = path
        self.include_paths = include_paths

    def __call__(self):
        with open(self.path, "rb") as f:
            data = f.read()
        cols = {"bytes": pa.array([data], pa.binary())}
        if self.include_paths:
            cols["path"] = pa.array([self.path], pa.string())
        return pa.table(cols)


class _ImageRead:
    """Decode one image file into an HxWxC uint8 row (reference:
    datasource/image_datasource.py via PIL)."""

    def __init__(self, path: str, size=None, mode: Optional[str] = None,
                 include_paths: bool = False):
        self.path = path
        self.size = size
        self.mode = mode
        self.include_paths = include_paths

    def __call__(self):
        from PIL import Image
        img = Image.open(self.path)
        if self.mode is not None:
            img = img.convert(self.mode)
        if self.size is not None:
            # reference semantics: size=(height, width); PIL takes (w, h)
            img = img.resize((self.size[1], self.size[0]))
        arr = np.asarray(img)
        cols = {"image": tensor_column([arr])}
        if self.include_paths:
            cols["path"] = pa.array([self.path], pa.string())
        return pa.table(cols)


class _NumpyRead:
    """One .npy file -> rows along axis 0 (reference: read_numpy)."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self):
        arr = np.load(self.path)
        if arr.ndim == 1:
            return pa.table({"data": pa.array(arr)})
        return pa.table({"data": stacked_tensor_column(arr)})


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def make_file_read_tasks(paths, fmt: str, columns=None, *,
                         expanded: bool = False) -> List[Callable]:
    """``expanded=True`` means the caller already ran expand_paths —
    re-expanding would re-glob literal filenames containing [?*
    metacharacters and silently drop them."""
    files = paths if expanded else expand_paths(paths)
    return [_FileRead(p, fmt, columns) for p in files]


class _FileWrite:
    """Writes one block to `<dir>/<uuid>-<i>.<ext>` (reference:
    datasource/parquet_datasink.py naming)."""

    def __init__(self, path: str, fmt: str, column=None):
        self.path, self.fmt = path, fmt
        self.column = column

    def __call__(self, block: pa.Table) -> str:
        import uuid
        os.makedirs(self.path, exist_ok=True)
        ext = {"numpy": "npy"}.get(self.fmt, self.fmt)
        name = f"{uuid.uuid4().hex[:12]}.{ext}"
        full = os.path.join(self.path, name)
        if self.fmt == "numpy":
            acc = BlockAccessor(block)
            arrs = acc.to_numpy([self.column] if self.column else None)
            arr = arrs[self.column] if self.column \
                else next(iter(arrs.values()))
            np.save(full, arr)
            return full
        if self.fmt in ("png", "jpeg", "jpg", "bmp"):
            # one image file per row (reference: write_images one file
            # per image, image_datasink.py)
            from PIL import Image
            acc = BlockAccessor(block)
            col = self.column or "image"
            arrs = acc.to_numpy([col])[col]
            stem = uuid.uuid4().hex[:12]
            last = ""  # zero-row block: no file written, say so
            for i, arr in enumerate(arrs):
                last = os.path.join(self.path,
                                    f"{stem}-{i:06d}.{self.fmt}")
                Image.fromarray(np.asarray(arr)).save(last)
            return last
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(block, full)
        elif self.fmt == "csv":
            import pyarrow.csv as pacsv
            pacsv.write_csv(block, full)
        elif self.fmt == "json":
            with open(full, "w") as f:
                import json
                for row in BlockAccessor(block).iter_rows():
                    f.write(json.dumps(_jsonable(row)) + "\n")
        else:
            raise ValueError(f"unknown format {self.fmt!r}")
        return full


def _jsonable(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out
