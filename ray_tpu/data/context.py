"""DataContext: per-session execution knobs.

reference: python/ray/data/context.py DataContext (thread-local current
context, copied into each Dataset at creation and shipped with tasks).
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class DataContext:
    # Block sizing (reference: data/context.py target_max_block_size).
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming executor limits.
    op_resource_budget_fraction: float = 1.0
    max_tasks_in_flight_per_op: int = 8
    max_blocks_in_op_output_queue: int = 32
    # Global queued-bytes budget for one stream; sources pause above it
    # (None = half the object store; see execution.ResourceManager).
    memory_budget_bytes: Optional[int] = None
    # Streaming shuffle: number of reduce partitions (None = min_parallelism),
    # how many map shards one reduce wave consumes, and the cap on map shard
    # sets held or being produced at once (clamped up to the fan-in so a
    # wave can always assemble).
    shuffle_num_reducers: Optional[int] = None
    shuffle_reduce_fanin: int = 4
    max_shuffle_blocks_in_flight: int = 16
    # Host-side prefetch depth for iter_batches / device staging depth for
    # iter_device_batches (both run a producer thread when > 0).
    iterator_prefetch_batches: int = 2
    device_prefetch_batches: int = 2
    # Defaults for map_batches.
    default_batch_format: str = "numpy"
    # Read parallelism when not specified.
    min_parallelism: int = 8
    # Whether the optimizer fuses adjacent map operators.
    enable_operator_fusion: bool = True
    # Fail or warn on exceptions inside UDFs.
    raise_on_udf_error: bool = True
    # Extra resources to attach to every data task.
    task_resources: Dict[str, float] = field(default_factory=dict)
    # Verbose progress (stdout) from the streaming executor.
    verbose_progress: bool = False

    _current = threading.local()

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._current, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._current.ctx = ctx
        return ctx

    @staticmethod
    def _set_current(ctx: "DataContext") -> None:
        DataContext._current.ctx = ctx

    def copy(self) -> "DataContext":
        return copy.deepcopy(self)
