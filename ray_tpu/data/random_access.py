"""Distributed random access over a sorted Dataset.

reference: python/ray/data/random_access_dataset.py — same public
surface (`RandomAccessDataset` with get_async/multiget/stats, built by
`Dataset.to_random_access_dataset`). Independent design: the dataset
is sorted by the key column and materialized; worker actors each pin a
contiguous run of blocks in memory; the driver keeps only the sorted
per-block key ranges and routes each lookup with a binary search, so
point reads cost one actor RPC + an O(log rows) searchsorted inside
the block.
"""
from __future__ import annotations

import bisect
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

__all__ = ["RandomAccessDataset"]


class _RandomAccessWorker:
    """Holds assigned blocks in memory, keyed by block index."""

    def __init__(self, key_field: str):
        self.key_field = key_field
        self.blocks: Dict[int, Any] = {}
        self.key_cols: Dict[int, np.ndarray] = {}
        self.num_gets = 0
        self.total_time = 0.0

    def assign_blocks(self, block_ref_dict: Dict[int, Any]):
        # dict-embedded ObjectRefs are NOT auto-resolved (top-level
        # args only) — fetch here so the tables are pinned in actor
        # memory and every later lookup is a local read. The key
        # column is materialized to numpy ONCE per block so point gets
        # are a true O(log rows) searchsorted, not an O(rows) copy.
        for i, ref in block_ref_dict.items():
            block = ray_tpu.get(ref)
            self.blocks[i] = block
            self.key_cols[i] = block.column(
                self.key_field).to_numpy(zero_copy_only=False)
        return len(self.blocks)

    def _lookup(self, block_index: int, key: Any):
        block = self.blocks.get(block_index)
        if block is None:
            return None
        col = self.key_cols[block_index]
        i = int(np.searchsorted(col, key))
        if i >= len(col) or col[i] != key:
            return None
        return {name: block.column(name)[i].as_py()
                for name in block.schema.names}

    def get(self, block_index: int, key: Any):
        t0 = time.perf_counter()
        try:
            return self._lookup(block_index, key)
        finally:
            self.num_gets += 1
            self.total_time += time.perf_counter() - t0

    def multiget(self, block_indices: List[int], keys: List[Any]):
        t0 = time.perf_counter()
        out = [self._lookup(b, k) for b, k in zip(block_indices, keys)]
        self.num_gets += len(keys)
        self.total_time += time.perf_counter() - t0
        return out

    def ping(self):
        return "ok"

    def stats(self) -> dict:
        return {"blocks": len(self.blocks), "num_gets": self.num_gets,
                "total_time": self.total_time}


class RandomAccessDataset:
    """Serve point lookups by key over a dataset.

    Args:
        ds: source Dataset (any order; it is sorted by ``key`` here).
        key: column to index on (values must be orderable).
        num_workers: actors holding the blocks (default 4).
    """

    def __init__(self, ds, key: str, *, num_workers: int = 4,
                 worker_options: Optional[dict] = None):
        self._key = key
        mat = ds.sort(key).materialize()
        refs = mat._refs

        # per-block [lo, hi] key ranges, computed remotely
        rng = ray_tpu.remote(
            lambda block, col=key: (
                (block.column(col)[0].as_py(),
                 block.column(col)[block.num_rows - 1].as_py())
                if block.num_rows else None))
        ranges = ray_tpu.get([rng.remote(r) for r in refs])
        keep = [i for i, r in enumerate(ranges) if r is not None]
        refs = [refs[i] for i in keep]
        self._ranges = [ranges[i] for i in keep]
        self._los = [r[0] for r in self._ranges]

        num_workers = max(1, min(num_workers, max(len(refs), 1)))
        opts = dict(worker_options or {})
        cls = ray_tpu.remote(_RandomAccessWorker)
        if opts:
            cls = cls.options(**opts)
        self._workers = [cls.remote(key) for _ in range(num_workers)]

        # contiguous runs of blocks per worker preserve range locality
        self._block_to_worker: List[int] = []
        assignments: List[Dict[int, Any]] = [
            {} for _ in range(num_workers)]
        for i, ref in enumerate(refs):
            w = min(i * num_workers // max(len(refs), 1),
                    num_workers - 1)
            assignments[w][i] = ref
            self._block_to_worker.append(w)
        ray_tpu.get([w.assign_blocks.remote(a)
                     for w, a in zip(self._workers, assignments)])
        # shared miss result: one store entry for every missed key
        self._none_ref = ray_tpu.put(None)

    # -- routing -------------------------------------------------------
    def _find_block(self, key: Any) -> Optional[int]:
        """Rightmost block whose low key <= key (ranges are sorted and
        disjoint after the global sort)."""
        i = bisect.bisect_right(self._los, key) - 1
        if i < 0:
            return None
        lo, hi = self._ranges[i]
        return i if lo <= key <= hi else None

    # -- reads ---------------------------------------------------------
    def get_async(self, key: Any):
        """ObjectRef resolving to the row dict, or None if absent."""
        b = self._find_block(key)
        if b is None:
            return self._none_ref
        worker = self._workers[self._block_to_worker[b]]
        return worker.get.remote(b, key)

    def multiget(self, keys: List[Any]) -> List[Optional[dict]]:
        """Batched lookups: one RPC per involved worker."""
        per_worker: Dict[int, List[int]] = {}
        blocks: List[Optional[int]] = []
        for pos, key in enumerate(keys):
            b = self._find_block(key)
            blocks.append(b)
            if b is not None:
                per_worker.setdefault(
                    self._block_to_worker[b], []).append(pos)
        results: List[Optional[dict]] = [None] * len(keys)
        futures = []
        for w, positions in per_worker.items():
            futures.append((positions, self._workers[w].multiget.remote(
                [blocks[p] for p in positions],
                [keys[p] for p in positions])))
        for positions, fut in futures:
            for p, row in zip(positions, ray_tpu.get(fut)):
                results[p] = row
        return results

    def stats(self) -> str:
        lines = [f"RandomAccessDataset(key={self._key!r}, "
                 f"blocks={len(self._ranges)}, "
                 f"workers={len(self._workers)})"]
        for i, s in enumerate(
                ray_tpu.get([w.stats.remote() for w in self._workers])):
            lines.append(f"  worker {i}: {s['blocks']} blocks, "
                         f"{s['num_gets']} gets, "
                         f"{s['total_time']:.4f}s")
        return "\n".join(lines)
