"""Expression API: declarative column computations.

Capability parity with the reference's expressions
(reference: python/ray/data/expressions.py — ``col``/``lit`` build Expr
trees combined with operators; ``Dataset.with_column`` evaluates them
vectorized). Evaluation lowers to pyarrow.compute kernels over whole
blocks — no per-row Python, and projections fuse with neighboring map
operators exactly like any other map_batches.

    from ray_tpu.data.expressions import col, lit
    ds = ds.with_column("z", col("x") * 2 + lit(1))
    ds = ds.filter(expr=col("z") > 10)
"""

from __future__ import annotations

from typing import Any

import pyarrow as pa
import pyarrow.compute as pc

_BINARY_KERNELS = {
    "+": pc.add,
    "-": pc.subtract,
    "*": pc.multiply,
    "/": pc.divide,
    "//": lambda a, b: pc.floor(pc.divide(a, b)),
    "%": lambda a, b: pc.subtract(
        a, pc.multiply(pc.floor(pc.divide(a, b)), b)),
    ">": pc.greater,
    ">=": pc.greater_equal,
    "<": pc.less,
    "<=": pc.less_equal,
    "==": pc.equal,
    "!=": pc.not_equal,
    "&": pc.and_kleene,
    "|": pc.or_kleene,
}


class Expr:
    """Base expression node; combine with Python operators."""

    def _bin(self, op: str, other, reverse: bool = False) -> "BinaryExpr":
        other = other if isinstance(other, Expr) else LiteralExpr(other)
        left, right = (other, self) if reverse else (self, other)
        return BinaryExpr(op, left, right)

    def __add__(self, other):
        return self._bin("+", other)

    def __radd__(self, other):
        return self._bin("+", other, reverse=True)

    def __sub__(self, other):
        return self._bin("-", other)

    def __rsub__(self, other):
        return self._bin("-", other, reverse=True)

    def __mul__(self, other):
        return self._bin("*", other)

    def __rmul__(self, other):
        return self._bin("*", other, reverse=True)

    def __truediv__(self, other):
        return self._bin("/", other)

    def __rtruediv__(self, other):
        return self._bin("/", other, reverse=True)

    def __floordiv__(self, other):
        return self._bin("//", other)

    def __mod__(self, other):
        return self._bin("%", other)

    def __gt__(self, other):
        return self._bin(">", other)

    def __ge__(self, other):
        return self._bin(">=", other)

    def __lt__(self, other):
        return self._bin("<", other)

    def __le__(self, other):
        return self._bin("<=", other)

    def __eq__(self, other):  # noqa: PYI032 — expression, not identity
        return self._bin("==", other)

    def __ne__(self, other):
        return self._bin("!=", other)

    def __and__(self, other):
        return self._bin("&", other)

    def __or__(self, other):
        return self._bin("|", other)

    def __invert__(self):
        return UnaryExpr("~", self)

    def __neg__(self):
        return UnaryExpr("neg", self)

    def __bool__(self):
        # Catch `expr1 and expr2` / chained comparisons, which would
        # otherwise SILENTLY evaluate to one operand (same guard as
        # pandas/pyarrow and the reference's expressions).
        raise TypeError(
            "Expr has no truth value; use & | ~ instead of and/or/not, "
            "and avoid chained comparisons")

    def __hash__(self):  # __eq__ builds exprs; keep nodes hashable
        return id(self)

    def eval(self, table: pa.Table):
        """Evaluate to a pyarrow array against a block."""
        raise NotImplementedError

    def is_function_of(self, column_names) -> bool:
        return all(c in column_names for c in self.columns())

    def columns(self) -> set:
        """Column names this expression reads."""
        raise NotImplementedError


class ColumnExpr(Expr):
    def __init__(self, name: str):
        self.name = name

    def eval(self, table: pa.Table):
        return table.column(self.name)

    def columns(self) -> set:
        return {self.name}

    def __repr__(self):
        return f"col({self.name!r})"


class LiteralExpr(Expr):
    def __init__(self, value: Any):
        self.value = value

    def eval(self, table: pa.Table):
        return pa.scalar(self.value)

    def columns(self) -> set:
        return set()

    def __repr__(self):
        return f"lit({self.value!r})"


class BinaryExpr(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def eval(self, table: pa.Table):
        kernel = _BINARY_KERNELS[self.op]
        return kernel(self.left.eval(table), self.right.eval(table))

    def columns(self) -> set:
        return self.left.columns() | self.right.columns()

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


class UnaryExpr(Expr):
    def __init__(self, op: str, operand: Expr):
        self.op = op
        self.operand = operand

    def eval(self, table: pa.Table):
        value = self.operand.eval(table)
        if self.op == "~":
            return pc.invert(value)
        if self.op == "neg":
            return pc.negate(value)
        raise ValueError(f"unknown unary op {self.op!r}")

    def columns(self) -> set:
        return self.operand.columns()

    def __repr__(self):
        return f"{self.op}{self.operand!r}"


def col(name: str) -> ColumnExpr:
    """Reference a column (reference: expressions.py col)."""
    return ColumnExpr(name)


def lit(value: Any) -> LiteralExpr:
    """A literal constant (reference: expressions.py lit)."""
    return LiteralExpr(value)


def _as_array(value, num_rows: int):
    """Broadcast scalars (pure-literal expressions) to column length."""
    if isinstance(value, pa.Scalar):
        return pa.repeat(value, num_rows)
    return value


class _WithColumnsFn:
    """Picklable block transform appending evaluated expressions."""

    def __init__(self, exprs):
        self.exprs = dict(exprs)

    def __call__(self, table: pa.Table) -> pa.Table:
        for name, expr in self.exprs.items():
            value = _as_array(expr.eval(table), table.num_rows)
            if name in table.column_names:
                idx = table.column_names.index(name)
                table = table.set_column(idx, name, value)
            else:
                table = table.append_column(name, value)
        return table


class _FilterExprFn:
    """Picklable block transform filtering by a boolean expression."""

    def __init__(self, expr):
        self.expr = expr

    def __call__(self, table: pa.Table) -> pa.Table:
        mask = _as_array(self.expr.eval(table), table.num_rows)
        return table.filter(mask)
