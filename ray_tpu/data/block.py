"""Block layer: the unit of distributed data.

A Block is a pyarrow.Table; BlockAccessor wraps one with the operations
the planner and executor need (slice/concat/convert/size accounting).
Capability parity with the reference's block model
(reference: python/ray/data/block.py, _internal/arrow_block.py,
_internal/pandas_block.py) with Arrow as the single canonical format —
pandas/numpy are converted at the edges, which keeps zero-copy numpy
views available for device feeding (tobatches -> jnp.asarray).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Batch = Union[pa.Table, "pandas.DataFrame", Dict[str, np.ndarray]]


@dataclass
class BlockMetadata:
    """Sidecar stats for a block (reference: data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[dict] = None


def tensor_column(arrs: List[np.ndarray], dtype=None,
                  ndim: Optional[int] = None) -> pa.Array:
    """Rows of equal-rank ndarrays -> typed nested-list arrow column.

    Preserves the numpy dtype (e.g. uint8 images stay uint8 — the
    reference's read_images semantics) instead of letting
    ``pa.array(tolist())`` widen everything to int64/float64. Pass
    ``dtype``/``ndim`` explicitly when ``arrs`` may be empty (a 0-row
    shard still needs a typed column).
    """
    if dtype is None:
        dtype, ndim = arrs[0].dtype, arrs[0].ndim
    try:
        typ = pa.from_numpy_dtype(dtype)
    except (pa.ArrowNotImplementedError, TypeError, ValueError):
        # object / unicode / other non-arrow dtypes: let arrow infer
        return pa.array([a.tolist() for a in arrs])
    for _ in range(ndim):
        typ = pa.list_(typ)
    return pa.array([a.tolist() for a in arrs], type=typ)


def stacked_tensor_column(arr: np.ndarray) -> pa.Array:
    """One stacked ndarray -> one column row per axis-0 slice."""
    return tensor_column(list(arr), dtype=arr.dtype, ndim=arr.ndim - 1)


def _tensor_column_to_numpy(col) -> Optional[np.ndarray]:
    """List-typed (tensor) column -> stacked [N, ...] ndarray with the
    original numeric dtype, or None if the column isn't tensor-shaped
    (not a list column, ragged rows, nulls, or non-numeric values).
    Rank-1 rows (token ids) come back [N, width]; higher ranks
    [N, d1, d2, ...].

    Fast path: when every list level has uniform offsets (uniform
    shapes, no nulls), reshape the flat values buffer directly —
    to_pylist() on an image column would build millions of Python
    scalars on the iter_batches -> device-feed path."""
    typ = col.type
    depth = 0
    while pa.types.is_list(typ) or pa.types.is_large_list(typ):
        typ = typ.value_type
        depth += 1
    if depth < 1:  # scalar columns: the plain path handles them
        return None
    try:
        dtype = np.dtype(typ.to_pandas_dtype())
    except (NotImplementedError, TypeError):
        return None
    if not (np.issubdtype(dtype, np.number) or dtype == np.bool_):
        return None
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    shape = [len(arr)]
    level = arr
    for _ in range(depth):
        if level.null_count:
            return None  # nulls: fall through to the generic path
        offsets = level.offsets.to_numpy()
        widths = np.diff(offsets)
        if len(widths) != len(level) or len(widths) == 0 or \
                not (widths == widths[0]).all():
            return None  # ragged (or offsets not aligned to this slice)
        shape.append(int(widths[0]))
        level = level.flatten()
    if level.null_count:  # nulls among the scalar values
        return None
    values = level.to_numpy(zero_copy_only=False)
    return values.reshape(shape).astype(dtype, copy=False)


def _normalize_rows(rows: Iterable[Any]) -> List[Dict[str, Any]]:
    out = []
    for r in rows:
        if isinstance(r, dict):
            out.append(r)
        else:
            out.append({"item": r})
    return out


class BlockAccessor:
    """Operations over one Arrow-table block."""

    def __init__(self, block: Block):
        if not isinstance(block, pa.Table):
            raise TypeError(f"Block must be a pyarrow.Table, got {type(block)}")
        self._table = block

    # -- construction -------------------------------------------------
    @staticmethod
    def from_rows(rows: Iterable[Any]) -> Block:
        rows = _normalize_rows(rows)
        if not rows:
            return pa.table({})
        cols: Dict[str, list] = {k: [] for k in rows[0]}
        for r in rows:
            for k in cols:
                cols[k].append(r.get(k))

        def _col(vals: list) -> pa.Array:
            # ndarray-valued rows (images, token arrays, …) become
            # typed nested-list columns; plain pa.array() raises on
            # anything multi-dimensional. Rows may disagree on dtype
            # (int rows mixed with float rows) — promote instead of
            # letting arrow truncate to the first row's type.
            if vals and all(isinstance(v, np.ndarray) and v.ndim >= 1
                            for v in vals):
                dtype = np.result_type(*[v.dtype for v in vals])
                return tensor_column(vals, dtype=dtype,
                                     ndim=vals[0].ndim)
            return pa.array(vals)

        return pa.table({k: _col(v) for k, v in cols.items()})

    @staticmethod
    def from_batch(batch: Batch) -> Block:
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            def _col(v):
                arr = np.asarray(v)
                if arr.ndim <= 1:
                    return pa.array(arr)
                return stacked_tensor_column(arr)
            return pa.table({k: _col(v) for k, v in batch.items()})
        # pandas
        return pa.Table.from_pandas(batch, preserve_index=False)

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
        if not blocks:
            return pa.table({})
        if len(blocks) == 1:
            return blocks[0]
        return pa.concat_tables(blocks, promote_options="default")

    # -- accessors ----------------------------------------------------
    @property
    def table(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def metadata(self, **kw) -> BlockMetadata:
        return BlockMetadata(num_rows=self.num_rows(),
                             size_bytes=self.size_bytes(),
                             schema=self.schema(), **kw)

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take_rows(self, indices: np.ndarray) -> Block:
        return self._table.take(pa.array(indices))

    # -- conversion ---------------------------------------------------
    def to_arrow(self) -> pa.Table:
        return self._table

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        cols = columns or self._table.column_names
        out = {}
        for name in cols:
            col = self._table.column(name)
            tensor = _tensor_column_to_numpy(col)
            if tensor is not None:
                out[name] = tensor
                continue
            try:
                arr = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                arr = np.asarray(col.to_pylist(), dtype=object)
            if arr.dtype == object and arr.size and isinstance(arr[0], np.ndarray):
                try:
                    arr = np.stack(arr)
                except ValueError:
                    pass
            out[name] = arr
        return out

    def to_batch(self, batch_format: str):
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self):
        for i in range(self._table.num_rows):
            yield {name: self._table.column(name)[i].as_py()
                   for name in self._table.column_names}

    def select(self, columns: List[str]) -> Block:
        return self._table.select(columns)

    def drop(self, columns: List[str]) -> Block:
        keep = [c for c in self._table.column_names if c not in columns]
        return self._table.select(keep)

    def rename(self, mapping: Dict[str, str]) -> Block:
        names = [mapping.get(c, c) for c in self._table.column_names]
        return self._table.rename_columns(names)

    def sort(self, key: Union[str, List[str]], descending: bool = False) -> Block:
        keys = [key] if isinstance(key, str) else list(key)
        order = "descending" if descending else "ascending"
        return self._table.sort_by([(k, order) for k in keys])

    def random_shuffle(self, seed: Optional[int]) -> Block:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._table.num_rows)
        return self._table.take(pa.array(perm))


def batch_to_block(batch: Batch) -> Block:
    return BlockAccessor.from_batch(batch)
