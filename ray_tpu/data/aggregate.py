"""Aggregation functions for groupby/global aggregation.

reference: python/ray/data/aggregate.py (AggregateFn, Count, Sum, Min,
Max, Mean, Std, Quantile) — here computed per reduce partition with
pyarrow groupby under the hood.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor


@dataclass
class AggregateFn:
    """One aggregation over a column (or rows, for Count)."""

    name: str          # output column name
    kind: str          # count | sum | min | max | mean | std | quantile
    on: Optional[str] = None
    q: float = 0.5     # quantile only


def Count():
    return AggregateFn(name="count()", kind="count")


def Sum(on: str):
    return AggregateFn(name=f"sum({on})", kind="sum", on=on)


def Min(on: str):
    return AggregateFn(name=f"min({on})", kind="min", on=on)


def Max(on: str):
    return AggregateFn(name=f"max({on})", kind="max", on=on)


def Mean(on: str):
    return AggregateFn(name=f"mean({on})", kind="mean", on=on)


def Std(on: str, ddof: int = 1):
    return AggregateFn(name=f"std({on})", kind="std", on=on, q=float(ddof))


def Quantile(on: str, q: float = 0.5):
    return AggregateFn(name=f"quantile({on})", kind="quantile", on=on, q=q)


def _agg_values(values: np.ndarray, agg: AggregateFn):
    if agg.kind == "count":
        return int(len(values))
    if len(values) == 0:
        return None
    if agg.kind == "sum":
        return values.sum()
    if agg.kind == "min":
        return values.min()
    if agg.kind == "max":
        return values.max()
    if agg.kind == "mean":
        return float(values.mean())
    if agg.kind == "std":
        ddof = int(agg.q)
        return float(values.std(ddof=ddof)) if len(values) > ddof else 0.0
    if agg.kind == "quantile":
        return float(np.quantile(values, agg.q))
    raise ValueError(f"unknown aggregate kind {agg.kind!r}")


def aggregate_block(block: Block, keys: List[str],
                    aggs: List[AggregateFn]) -> Block:
    """Aggregate one (hash-partitioned) block; rows grouped by `keys`."""
    acc = BlockAccessor(block)
    if not keys:
        cols = {}
        for agg in aggs:
            vals = (acc.to_numpy([agg.on])[agg.on]
                    if agg.on else np.empty(acc.num_rows()))
            if agg.on is None and agg.kind == "count":
                vals = np.empty(acc.num_rows())
            cols[agg.name] = [_agg_values(vals, agg)]
        return pa.table({k: pa.array(v) for k, v in cols.items()})

    if acc.num_rows() == 0:
        return pa.table({})

    key_cols = [block.column(k).to_pylist() for k in keys]
    key_tuples = list(zip(*key_cols))
    groups = {}
    for i, kt in enumerate(key_tuples):
        groups.setdefault(kt, []).append(i)
    sorted_keys = sorted(groups.keys())
    out = {k: [] for k in keys}
    for agg in aggs:
        out[agg.name] = []
    col_cache = {}
    for agg in aggs:
        if agg.on and agg.on not in col_cache:
            col_cache[agg.on] = acc.to_numpy([agg.on])[agg.on]
    for kt in sorted_keys:
        idx = np.asarray(groups[kt], dtype=np.int64)
        for j, k in enumerate(keys):
            out[k].append(kt[j])
        for agg in aggs:
            vals = col_cache[agg.on][idx] if agg.on else np.empty(len(idx))
            out[agg.name].append(_agg_values(vals, agg))
    return pa.table({k: pa.array(v) for k, v in out.items()})
