"""Fused per-block transform chains, applied inside data tasks.

reference: python/ray/data/_internal/planner/plan_udf_map_op.py — the
planner fuses adjacent row/batch transforms into one chain that a single
task applies to a block, avoiding a task hop (and an object-store round
trip) per logical operator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, batch_to_block


@dataclass
class MapTransform:
    kind: str  # map_rows | map_batches | filter | flat_map | select | drop | rename | add_column
    fn: Any = None
    fn_args: Tuple = ()
    fn_kwargs: Dict = field(default_factory=dict)
    batch_size: Optional[int] = None
    batch_format: str = "numpy"


def _apply_batches(block: Block, t: MapTransform) -> Block:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    size = t.batch_size or max(n, 1)
    out_blocks: List[Block] = []
    for start in range(0, max(n, 1), size):
        piece = acc.slice(start, min(start + size, n)) if n else block
        batch = BlockAccessor(piece).to_batch(t.batch_format)
        result = t.fn(batch, *t.fn_args, **t.fn_kwargs)
        if result is None:
            continue
        if hasattr(result, "__next__") or (
                hasattr(result, "__iter__")
                and not isinstance(result, (dict, pa.Table, list))
                and not hasattr(result, "columns")):
            for r in result:
                out_blocks.append(batch_to_block(r))
        else:
            out_blocks.append(batch_to_block(result))
        if n == 0:
            break
    if not out_blocks:
        return pa.table({})
    return BlockAccessor.concat(out_blocks)


def _apply_rows(block: Block, t: MapTransform) -> Block:
    acc = BlockAccessor(block)
    out_rows: List[dict] = []
    for row in acc.iter_rows():
        if t.kind == "map_rows":
            out_rows.append(t.fn(row, *t.fn_args, **t.fn_kwargs))
        elif t.kind == "filter":
            if t.fn(row, *t.fn_args, **t.fn_kwargs):
                out_rows.append(row)
        elif t.kind == "flat_map":
            out_rows.extend(t.fn(row, *t.fn_args, **t.fn_kwargs))
    if not out_rows and acc.num_rows():
        return block.schema.empty_table()
    return BlockAccessor.from_rows(out_rows)


def apply_transform_chain(block: Block, transforms: List[MapTransform]) -> Block:
    for t in transforms:
        if t.kind == "map_batches":
            block = _apply_batches(block, t)
        elif t.kind in ("map_rows", "filter", "flat_map"):
            block = _apply_rows(block, t)
        elif t.kind == "select":
            block = BlockAccessor(block).select(t.fn)
        elif t.kind == "drop":
            block = BlockAccessor(block).drop(t.fn)
        elif t.kind == "rename":
            block = BlockAccessor(block).rename(t.fn)
        elif t.kind == "add_column":
            name, fn = t.fn
            batch = BlockAccessor(block).to_numpy()
            col = fn(batch)
            block = block.append_column(name, pa.array(np.asarray(col)))
        else:
            raise ValueError(f"unknown transform kind {t.kind!r}")
    return block
