"""Preprocessors: fit statistics on a Dataset, transform batches.

Capability parity with the reference's preprocessor library
(reference: python/ray/data/preprocessors/ — Preprocessor base with
fit/transform/fit_transform, scalers.py StandardScaler/MinMaxScaler,
encoders.py LabelEncoder/OneHotEncoder, concatenator.py, chain.py).
Fitting runs as distributed aggregates over the Dataset; transforming
is a map_batches over numpy batches, so a fitted preprocessor chains
straight into iter_batches / to_jax pipelines.

    scaler = StandardScaler(columns=["x"]).fit(ds)
    for batch in scaler.transform(ds).iter_batches():
        ...
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    """Base: subclasses implement _fit (optional) + transform_batch.
    Statelessness is detected from the class: no _fit override means
    transform() works without fit()."""

    def __init__(self):
        self._fitted = False

    @property
    def _fittable(self) -> bool:
        return type(self)._fit is not Preprocessor._fit

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if self._fittable and not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit() before transform()")
        return ds.map_batches(self.transform_batch)

    # -- subclass hooks --------------------------------------------------
    def _fit(self, ds) -> None:
        pass  # default: stateless

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: scalers.py
    StandardScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        self.stats_ = {
            c: (float(ds.mean(c)), float(ds.std(c) or 0.0))
            for c in self.columns}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            denom = std if std > 0 else 1.0
            out[c] = (np.asarray(batch[c], np.float64) - mean) / denom
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference: scalers.py
    MinMaxScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        self.stats_ = {c: (float(ds.min(c)), float(ds.max(c)))
                       for c in self.columns}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = (hi - lo) if hi > lo else 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (reference: encoders.py
    LabelEncoder)."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column
        self.mapping_: Dict[Any, int] = {}

    def _fit(self, ds) -> None:
        values = sorted(ds.unique(self.label_column))
        self.mapping_ = {v: i for i, v in enumerate(values)}

    def transform_batch(self, batch):
        out = dict(batch)
        cats = np.asarray(sorted(self.mapping_))
        vals = np.asarray(batch[self.label_column])
        idx = np.searchsorted(cats, vals)
        clipped = np.clip(idx, 0, len(cats) - 1)
        unseen = cats[clipped] != vals
        if unseen.any():
            sample = sorted(set(np.asarray(vals)[unseen][:5].tolist()))
            raise ValueError(
                f"LabelEncoder: label(s) {sample} in column "
                f"{self.label_column!r} were not seen during fit()")
        out[self.label_column] = clipped.astype(np.int64)
        return out

    def inverse_transform_batch(self, batch):
        inverse = {i: v for v, i in self.mapping_.items()}
        out = dict(batch)
        out[self.label_column] = np.asarray(
            [inverse[int(i)] for i in batch[self.label_column]])
        return out


class OneHotEncoder(Preprocessor):
    """Categorical columns -> {col}_{value} indicator columns
    (reference: encoders.py OneHotEncoder)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)
        self.categories_: Dict[str, List[Any]] = {}

    def _fit(self, ds) -> None:
        self.categories_ = {c: sorted(ds.unique(c)) for c in self.columns}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            values = np.asarray(out.pop(c))
            for cat in self.categories_[c]:
                out[f"{c}_{cat}"] = (values == cat).astype(np.int8)
        return out


class Concatenator(Preprocessor):
    """Merge numeric columns into one feature-vector column
    (reference: concatenator.py — the standard last step before
    feeding a model). Stateless: no _fit override."""

    def __init__(self, columns: Optional[List[str]] = None,
                 output_column_name: str = "concat",
                 dtype=np.float32, exclude: Optional[List[str]] = None):
        super().__init__()
        self.columns = list(columns) if columns else None
        self.output_column_name = output_column_name
        self.dtype = dtype
        self.exclude = set(exclude or ())

    def transform_batch(self, batch):
        cols = (self.columns if self.columns is not None
                else [c for c in batch if c not in self.exclude])
        parts = []
        for c in cols:
            arr = np.asarray(batch[c])
            parts.append(arr.reshape(len(arr), -1))
        out = {k: v for k, v in batch.items()
               if k not in cols}
        out[self.output_column_name] = np.concatenate(
            parts, axis=1).astype(self.dtype)
        return out


class Chain(Preprocessor):
    """Run preprocessors in sequence; fit is staged so each stage fits
    on the PREVIOUS stages' transformed data (reference: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def fit(self, ds) -> "Chain":
        for prep in self.preprocessors:
            if prep._fittable:
                prep.fit(ds)
            ds = prep.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted:
            raise PreprocessorNotFittedError(
                "Chain must be fit() before transform()")
        for prep in self.preprocessors:
            ds = prep.transform(ds)
        return ds

    def transform_batch(self, batch):
        for prep in self.preprocessors:
            batch = prep.transform_batch(batch)
        return batch
