"""Logical plan: a DAG of declarative operators built by the Dataset API.

reference: python/ray/data/_internal/logical/operators/*.py and
logical/interfaces.py — each Dataset op appends a LogicalOperator; the
planner lowers the DAG to physical operators at execution time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    """One node of the logical DAG (single-input chain plus n-ary ops)."""

    def __init__(self, name: str, inputs: List["LogicalOp"]):
        self.name = name
        self.inputs = inputs

    def __repr__(self):
        return f"{self.name}({', '.join(i.name for i in self.inputs)})"


class Read(LogicalOp):
    """Leaf: produces blocks from a datasource's read tasks."""

    def __init__(self, read_tasks: List[Callable[[], Any]],
                 name: str = "Read", input_files=None):
        super().__init__(name, [])
        self.read_tasks = read_tasks
        # source file paths, when the datasource is file-backed
        # (reference: dataset.py input_files from block metadata)
        self.input_files = list(input_files or [])


class InputData(LogicalOp):
    """Leaf: blocks already in the object store (from_items / from_pandas)."""

    def __init__(self, block_refs: List[Any], metadata: List[Any]):
        super().__init__("InputData", [])
        self.block_refs = block_refs
        self.metadata = metadata


class AbstractMap(LogicalOp):
    """Row/batch transform; fusable with adjacent maps.

    kind: one of "map_rows", "map_batches", "filter", "flat_map",
    "select", "drop", "rename", "add_column".
    """

    def __init__(self, kind: str, fn: Any, input_op: LogicalOp, *,
                 fn_args: Tuple = (), fn_kwargs: Optional[Dict] = None,
                 batch_size: Optional[int] = None,
                 batch_format: Optional[str] = None,
                 compute: str = "tasks", concurrency: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 name: Optional[str] = None):
        super().__init__(name or kind, [input_op])
        self.kind = kind
        self.fn = fn
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.compute = compute
        self.concurrency = concurrency
        self.resources = resources or {}


class AbstractAllToAll(LogicalOp):
    """Barrier op over the whole stream (shuffle/sort/repartition/groupby)."""

    def __init__(self, kind: str, input_op: LogicalOp, *,
                 num_outputs: Optional[int] = None,
                 key: Any = None, descending: bool = False,
                 seed: Optional[int] = None,
                 aggs: Optional[List[Any]] = None,
                 name: Optional[str] = None):
        super().__init__(name or kind, [input_op])
        self.kind = kind  # repartition | random_shuffle | sort | aggregate
        self.num_outputs = num_outputs
        self.key = key
        self.descending = descending
        self.seed = seed
        self.aggs = aggs or []


class Limit(LogicalOp):
    def __init__(self, input_op: LogicalOp, limit: int):
        super().__init__(f"Limit[{limit}]", [input_op])
        self.limit = limit


class Union(LogicalOp):
    def __init__(self, inputs: List[LogicalOp]):
        super().__init__("Union", inputs)


class Join(LogicalOp):
    def __init__(self, left: "LogicalOp", right: "LogicalOp", *,
                 on, how: str = "inner", num_partitions=None):
        super().__init__(f"Join({how})", [left, right])
        self.on = [on] if isinstance(on, str) else list(on)
        self.how = how
        self.num_partitions = num_partitions


class Zip(LogicalOp):
    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__("Zip", [left, right])


class Write(LogicalOp):
    def __init__(self, input_op: LogicalOp, write_fn: Callable,
                 name: str = "Write"):
        super().__init__(name, [input_op])
        self.write_fn = write_fn


@dataclass
class LogicalPlan:
    dag: LogicalOp

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(dag=op)

    def ops_topological(self) -> List[LogicalOp]:
        seen: Dict[int, LogicalOp] = {}
        order: List[LogicalOp] = []

        def visit(op: LogicalOp):
            if id(op) in seen:
                return
            seen[id(op)] = op
            for inp in op.inputs:
                visit(inp)
            order.append(op)

        visit(self.dag)
        return order

    def explain(self) -> str:
        lines = []
        for i, op in enumerate(self.ops_topological()):
            lines.append(f"{i}: {op!r}")
        return "\n".join(lines)
