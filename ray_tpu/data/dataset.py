"""The public Dataset: a lazy, distributed collection of Arrow blocks.

reference: python/ray/data/dataset.py — transformations append logical
operators (lazy); consumption plans + streams execution
(iter_batches:5162, streaming_split:1853, materialize, take, count).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.execution import RefBundle, StreamingExecutor
from ray_tpu.data.iterator import (
    DataIterator,
    _ExecutionIterator,
    iter_batches_from_blocks,
    make_streaming_split,
)
from ray_tpu.data.planner import Planner


class Dataset:
    def __init__(self, plan: L.LogicalPlan,
                 context: Optional[DataContext] = None):
        self._plan = plan
        self._context = context or DataContext.get_current().copy()

    # -- plan construction helpers -----------------------------------
    def _with_op(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(L.LogicalPlan(op), self._context)

    @property
    def context(self) -> DataContext:
        return self._context

    # -- transformations (lazy) --------------------------------------
    def map(self, fn: Callable[[dict], dict], **opts) -> "Dataset":
        return self._with_op(self._map_op("map_rows", fn, **opts))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None,
                    compute: Optional[str] = None,
                    concurrency: Union[int, Tuple[int, int], None] = None,
                    fn_args=(), fn_kwargs=None,
                    num_cpus: Optional[float] = None,
                    resources: Optional[Dict[str, float]] = None,
                    **_ignored) -> "Dataset":
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = num_cpus
        # Callable classes run in long-lived actors (reference:
        # dataset.py map_batches compute=ActorPoolStrategy).
        if compute is None:
            compute = "actors" if isinstance(fn, type) else "tasks"
        if isinstance(fn, type):
            fn = _CallableClassWrapper(fn, fn_args, fn_kwargs or {})
            fn_args, fn_kwargs = (), {}
        op = L.AbstractMap(
            "map_batches", fn, self._plan.dag, fn_args=tuple(fn_args),
            fn_kwargs=fn_kwargs or {}, batch_size=batch_size,
            batch_format=batch_format, compute=compute,
            concurrency=concurrency, resources=res)
        return self._with_op(op)

    def _map_op(self, kind: str, fn, **opts) -> L.AbstractMap:
        return L.AbstractMap(kind, fn, self._plan.dag, **opts)

    def filter(self, fn: Optional[Callable[[dict], bool]] = None, *,
               expr=None, **opts) -> "Dataset":
        """Row predicate (Python fn) or vectorized expression filter
        (reference: dataset.py filter(expr=...) over
        data/expressions.py)."""
        if expr is not None:
            if fn is not None:
                raise ValueError("pass either fn or expr, not both")
            from ray_tpu.data.expressions import _FilterExprFn
            return self.map_batches(_FilterExprFn(expr),
                                    batch_format="pyarrow", **opts)
        return self._with_op(self._map_op("filter", fn, **opts))

    def flat_map(self, fn: Callable[[dict], Iterable[dict]], **opts) -> "Dataset":
        return self._with_op(self._map_op("flat_map", fn, **opts))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with_op(self._map_op("select", list(cols)))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._with_op(self._map_op("drop", list(cols)))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._with_op(self._map_op("rename", dict(mapping)))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self._with_op(self._map_op("add_column", (name, fn)))

    def with_column(self, name: str, expr) -> "Dataset":
        """Append/replace a column computed from an expression,
        vectorized over blocks (reference: dataset.py with_column +
        data/expressions.py)."""
        return self.with_columns(**{name: expr})

    def with_columns(self, **exprs) -> "Dataset":
        from ray_tpu.data.expressions import _WithColumnsFn
        return self.map_batches(_WithColumnsFn(exprs),
                                batch_format="pyarrow")

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(L.AbstractAllToAll(
            "repartition", self._plan.dag, num_outputs=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._with_op(L.AbstractAllToAll(
            "random_shuffle", self._plan.dag, seed=seed,
            num_outputs=num_blocks))

    def sort(self, key: Union[str, List[str]],
             descending: bool = False) -> "Dataset":
        return self._with_op(L.AbstractAllToAll(
            "sort", self._plan.dag, key=key, descending=descending))

    def groupby(self, key: Union[str, List[str]]) -> "GroupedData":
        return GroupedData(self, key)

    def limit(self, n: int) -> "Dataset":
        return self._with_op(L.Limit(self._plan.dag, n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with_op(L.Union(
            [self._plan.dag] + [o._plan.dag for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with_op(L.Zip(self._plan.dag, other._plan.dag))

    def join(self, other: "Dataset", on: Union[str, List[str]], *,
             how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Hash join on key column(s). ``how``: inner | left | right |
        outer. Both sides are hash-partitioned on ``on`` and partitions
        join independently (reference: Dataset.join backed by the
        hash-shuffle join operator, data/_internal/execution/operators/
        join.py). Identically-named non-key columns from ``other`` get
        an ``_r`` suffix."""
        return self._with_op(L.Join(self._plan.dag, other._plan.dag,
                                    on=on, how=how,
                                    num_partitions=num_partitions))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        rng_seed = seed if seed is not None else 0x5EED

        def sample(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
            n = len(next(iter(batch.values()))) if batch else 0
            rng = np.random.default_rng(rng_seed + n)
            keep = rng.random(n) < fraction
            return {k: np.asarray(v)[keep] for k, v in batch.items()}

        return self.map_batches(sample, batch_format="numpy")

    # -- execution ----------------------------------------------------
    def _execute_stream(self):
        DataContext._set_current(self._context)
        physical = Planner(self._context).plan(self._plan)
        executor = StreamingExecutor(physical, self._context)
        # Kept for introspection (tests/bench read per-op streaming
        # stats, e.g. shuffle peak in-flight blocks) — not an API.
        self._last_executor = executor
        return executor.execute()

    def iter_internal_ref_bundles(self):
        return self._execute_stream()

    def materialize(self) -> "MaterializedDataset":
        bundles = list(self._execute_stream())
        refs = [b.block_ref for b in bundles]
        metas = [b.metadata for b in bundles]
        plan = L.LogicalPlan(L.InputData(refs, metas))
        return MaterializedDataset(plan, self._context, refs, metas)

    # -- consumption ---------------------------------------------------
    def iterator(self) -> DataIterator:
        return _ExecutionIterator(self)

    def iter_rows(self):
        return self.iterator().iter_rows()

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def iter_device_batches(self, **kw):
        return self.iterator().iter_device_batches(**kw)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[DataIterator]:
        return make_streaming_split(self, n, equal)

    def split(self, n: int) -> List["MaterializedDataset"]:
        mat = self.materialize()
        out = []
        for i in range(n):
            refs = mat._refs[i::n]
            metas = mat._metas[i::n]
            plan = L.LogicalPlan(L.InputData(refs, metas))
            out.append(MaterializedDataset(plan, self._context, refs, metas))
        return out

    def split_at_indices(self, indices: List[int]) -> List["MaterializedDataset"]:
        """Split by global row indices into len(indices)+1 datasets
        (reference: dataset.py split_at_indices)."""
        if any(i < 0 for i in indices):
            raise ValueError("indices must be nonnegative")
        if sorted(indices) != list(indices):
            raise ValueError("indices must be sorted in increasing order")
        mat = self.materialize()
        # Boundaries come from block METADATA — whole blocks keep their
        # existing refs and only boundary-straddling blocks are sliced,
        # remotely, so no block payload ever crosses the driver.
        slicer = ray_tpu.remote(
            lambda block, s, e: BlockAccessor(block).slice(s, e))
        bounds = list(indices) + [float("inf")]
        splits: List[List[Any]] = [[] for _ in bounds]  # (ref, meta)
        si = 0
        row_pos = 0
        for ref, meta in zip(mat._refs, mat._metas):
            n = meta.num_rows
            off = 0
            while off < n:
                take = int(min(n - off, bounds[si] - row_pos))
                if take <= 0:
                    si += 1
                    continue
                if take == n and off == 0:
                    splits[si].append((ref, meta))
                else:
                    pm = BlockMetadata(
                        num_rows=take,
                        size_bytes=max(1, meta.size_bytes * take
                                       // max(n, 1)),
                        schema=meta.schema)
                    splits[si].append(
                        (slicer.remote(ref, off, off + take), pm))
                off += take
                row_pos += take
                if si < len(indices) and row_pos >= bounds[si]:
                    si += 1
        out = []
        for pieces in splits:
            refs = [r for r, _ in pieces]
            metas = [m for _, m in pieces]
            plan = L.LogicalPlan(L.InputData(refs, metas))
            out.append(MaterializedDataset(plan, self._context, refs, metas))
        return out

    def split_proportionately(self, proportions: List[float]
                              ) -> List["MaterializedDataset"]:
        """Split by fractions; the remainder becomes the final split
        (reference: dataset.py split_proportionately)."""
        if not proportions:
            raise ValueError("proportions must not be empty")
        if any(p <= 0 for p in proportions):
            raise ValueError("proportions must be positive")
        if sum(proportions) >= 1.0:
            raise ValueError("sum of proportions must be < 1")
        mat = self.materialize()
        n = mat.count()
        indices = []
        cum = 0.0
        for p in proportions:
            cum += p
            indices.append(min(n, int(n * cum)))
        return mat.split_at_indices(indices)

    def train_test_split(self, test_size: Union[int, float], *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> List["MaterializedDataset"]:
        """Return [train, test] (reference: dataset.py
        train_test_split)."""
        ds: Dataset = self
        if shuffle:
            ds = ds.random_shuffle(seed=seed)
        if isinstance(test_size, float):
            if not 0 < test_size < 1:
                raise ValueError("test_size fraction must be in (0, 1)")
            return ds.split_proportionately([1.0 - test_size])
        if test_size <= 0:
            raise ValueError("test_size must be positive")
        # Materialize once: count comes from block metadata, and
        # split_at_indices on the materialized set is a replay, not a
        # second pipeline execution.
        mat = ds.materialize()
        n = mat.count()
        if test_size >= n:
            raise ValueError(f"test_size {test_size} >= dataset size {n}")
        return mat.split_at_indices([n - test_size])

    def to_random_access_dataset(self, key: str, *,
                                 num_workers: int = 4,
                                 worker_options: Optional[dict] = None):
        """Sort by ``key`` and pin the blocks across worker actors for
        distributed point lookups (reference: dataset.py
        to_random_access_dataset / random_access_dataset.py)."""
        from ray_tpu.data.random_access import RandomAccessDataset
        return RandomAccessDataset(self, key, num_workers=num_workers,
                                   worker_options=worker_options)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "MaterializedDataset":
        """Shuffle whole blocks without touching rows — the cheap
        decorrelator before windowed iteration (reference: dataset.py
        randomize_block_order)."""
        mat = self.materialize()
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(mat._refs))
        refs = [mat._refs[i] for i in order]
        metas = [mat._metas[i] for i in order]
        plan = L.LogicalPlan(L.InputData(refs, metas))
        return MaterializedDataset(plan, self._context, refs, metas)

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return list(self.iter_rows())

    def take_batch(self, n: int = 20, batch_format: str = "numpy"):
        for batch in self.limit(n).iter_batches(batch_size=n,
                                                batch_format=batch_format):
            return batch
        return {}

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        total = 0
        for bundle in self._execute_stream():
            total += bundle.metadata.num_rows
        return total

    def schema(self) -> Optional[pa.Schema]:
        for bundle in self.limit(1)._execute_stream():
            block = ray_tpu.get(bundle.block_ref)
            return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def num_blocks(self) -> int:
        return sum(1 for _ in self._execute_stream())

    def size_bytes(self) -> int:
        return sum(b.metadata.size_bytes for b in self._execute_stream())

    # -- aggregations --------------------------------------------------
    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        ds = self._with_op(L.AbstractAllToAll(
            "aggregate", self._plan.dag, key=None, aggs=list(aggs)))
        rows = ds.take_all()
        return rows[0] if rows else {}

    def sum(self, on: str):
        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str):
        return self.aggregate(Std(on)).get(f"std({on})")

    def unique(self, column: str) -> List[Any]:
        seen = set()
        for row in self.select_columns([column]).iter_rows():
            seen.add(row[column])
        return sorted(seen)

    # -- output --------------------------------------------------------
    def to_pandas(self, limit: Optional[int] = None):
        ds = self.limit(limit) if limit else self
        refs = [b.block_ref for b in ds._execute_stream()]
        blocks = ray_tpu.get(refs) if refs else []
        if not blocks:
            return pa.table({}).to_pandas()
        return BlockAccessor.concat(blocks).to_pandas()

    def to_arrow_refs(self) -> List[Any]:
        return [b.block_ref for b in self._execute_stream()]

    def to_pandas_refs(self) -> List[Any]:
        """One ObjectRef per block, each resolving to a DataFrame —
        conversion runs remotely (reference: dataset.py
        to_pandas_refs)."""
        to_df = ray_tpu.remote(
            lambda block: BlockAccessor(block).to_pandas())
        return [to_df.remote(b.block_ref) for b in self._execute_stream()]

    def to_numpy_refs(self, *, column: Optional[str] = None) -> List[Any]:
        """One ObjectRef per block resolving to an ndarray (``column``
        given) or a {column: ndarray} dict (reference: dataset.py
        to_numpy_refs)."""
        def conv(block, col=column):
            arrs = BlockAccessor(block).to_numpy([col] if col else None)
            return arrs[col] if col else arrs
        to_np = ray_tpu.remote(conv)
        return [to_np.remote(b.block_ref) for b in self._execute_stream()]

    def input_files(self) -> List[str]:
        """Source file paths feeding this dataset's Read leaves
        (reference: dataset.py input_files)."""
        files: List[str] = []
        seen = set()
        stack = [self._plan.dag]
        while stack:
            op = stack.pop()
            for f in getattr(op, "input_files", []) or []:
                if f not in seen:
                    seen.add(f)
                    files.append(f)
            stack.extend(getattr(op, "inputs", []) or [])
        return files

    def names(self) -> Optional[List[str]]:
        """Column names (reference: dataset.py names)."""
        s = self.schema()
        return list(s.names) if s is not None else None

    def types(self) -> Optional[List[Any]]:
        """Arrow column types, parallel to names() (reference:
        dataset.py schema().types)."""
        s = self.schema()
        return list(s.types) if s is not None else None

    # -- naming + plan introspection -----------------------------------
    @property
    def name(self) -> Optional[str]:
        return getattr(self, "_name", None)

    def set_name(self, name: Optional[str]) -> None:
        self._name = name

    def explain(self) -> str:
        """Logical plan rendering; printed by the reference's
        Dataset.explain, returned here for asserting in tests."""
        text = self._plan.explain()
        print(text)
        return text

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def write_numpy(self, path: str, *,
                    column: Optional[str] = None) -> None:
        """One .npy per block from ``column`` (default: the first
        column) (reference: dataset.py write_numpy)."""
        self._write(path, "numpy", column=column)

    def write_images(self, path: str, column: str = "image",
                     file_format: str = "png") -> None:
        """One image file per row (reference: dataset.py
        write_images)."""
        if file_format not in ("png", "jpeg", "jpg", "bmp"):
            raise ValueError(f"unsupported image format {file_format!r}")
        self._write(path, file_format, column=column)

    def write_datasink(self, sink) -> None:
        """Write through a user-defined Datasink (reference:
        dataset.py write_datasink over the public Datasink ABC):
        ``sink.write`` runs once per block as a task, then
        ``sink.on_write_complete`` runs here with the per-block
        results."""
        from ray_tpu.data.datasource import Datasink
        if not isinstance(sink, Datasink):
            raise ValueError("write_datasink takes a ray_tpu.data.Datasink")
        ds = self._with_op(L.Write(self._plan.dag, sink.write,
                                   name=f"Write[{type(sink).__name__}]"))
        results = []
        for bundle in ds._execute_stream():
            acc = BlockAccessor(ray_tpu.get(bundle.block_ref))
            results.extend(row.get("write_result")
                           for row in acc.iter_rows())
        sink.on_write_complete(results)

    def write_tfrecords(self, path: str) -> None:
        """One .tfrecords file of tf.train.Example records per block
        (reference: dataset.py write_tfrecords)."""
        from ray_tpu.data.datasource import TFRecordDatasink
        self.write_datasink(TFRecordDatasink(path))

    def write_sql(self, sql: str, connection_factory) -> None:
        """executemany an INSERT per block over a DB-API connection
        (reference: dataset.py write_sql)."""
        from ray_tpu.data.datasource import SQLDatasink
        self.write_datasink(SQLDatasink(sql, connection_factory))

    def _write(self, path: str, fmt: str, column=None) -> None:
        from ray_tpu.data.datasource import _FileWrite
        ds = self._with_op(L.Write(self._plan.dag,
                                   _FileWrite(path, fmt, column),
                                   name=f"Write[{fmt}]"))
        for _ in ds._execute_stream():
            pass

    def stats(self) -> str:
        return self._plan.explain()

    def __repr__(self):
        return f"Dataset(plan={self._plan.dag!r})"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already in the object store
    (reference: data/dataset.py MaterializedDataset)."""

    def __init__(self, plan, context, refs, metas):
        super().__init__(plan, context)
        self._refs = refs
        self._metas = metas

    def num_blocks(self) -> int:
        return len(self._refs)

    def count(self) -> int:
        return sum(m.num_rows for m in self._metas)


class _CallableClassWrapper:
    """Instantiates a callable class once per worker process
    (reference: map actors construct the UDF class in the actor)."""

    def __init__(self, cls, args, kwargs):
        self.cls, self.args, self.kwargs = cls, args, kwargs
        self._instance = None

    def __call__(self, batch, *a, **kw):
        if self._instance is None:
            self._instance = self.cls(*self.args, **self.kwargs)
        return self._instance(batch, *a, **kw)


class GroupedData:
    """reference: python/ray/data/grouped_data.py"""

    def __init__(self, dataset: Dataset, key):
        self._dataset = dataset
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._dataset._with_op(L.AbstractAllToAll(
            "aggregate", self._dataset._plan.dag, key=self._key,
            aggs=list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key
        keys = [key] if isinstance(key, str) else list(key)

        def apply_groups(batch: Dict[str, np.ndarray]):
            if not batch:
                return batch
            import pandas as pd
            df = pa.table({k: pa.array(np.asarray(v))
                           for k, v in batch.items()}).to_pandas()
            outs = []
            for _, group in df.groupby(keys, sort=True):
                res = fn({c: group[c].to_numpy() for c in group.columns})
                outs.append(res)
            merged: Dict[str, list] = {}
            for o in outs:
                for k, v in o.items():
                    merged.setdefault(k, []).extend(np.asarray(v).tolist())
            return {k: np.asarray(v) for k, v in merged.items()}

        # Repartition by key first so each group lands in one block.
        ds = self._dataset.sort(keys)
        return ds.map_batches(apply_groups, batch_format="numpy")
