"""DataIterator: batch iteration and streaming_split.

reference: python/ray/data/iterator.py:106 (iter_batches with
batch_size/format/local shuffle, iter_torch_batches) and
dataset.py:1853 streaming_split — n consumers fed from one execution via
a coordinator actor (reference: _internal/execution/streaming_split
output_splitter.py). Device feeding for TPU: `iter_device_batches`
yields jax arrays staged host->HBM with double buffering.
"""

from __future__ import annotations

import threading

from ray_tpu.devtools import locktrace
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def _slice_concat(blocks: deque, batch_size: int) -> Optional[Block]:
    """Pop exactly batch_size rows off the front of `blocks` (concat as
    needed); returns None if fewer rows are buffered."""
    have = sum(b.num_rows for b in blocks)
    if have < batch_size:
        return None
    parts, need = [], batch_size
    while need > 0:
        b = blocks.popleft()
        if b.num_rows <= need:
            parts.append(b)
            need -= b.num_rows
        else:
            parts.append(BlockAccessor(b).slice(0, need))
            blocks.appendleft(BlockAccessor(b).slice(need, b.num_rows))
            need = 0
    return BlockAccessor.concat(parts)


def iter_batches_from_blocks(block_iter, *, batch_size: Optional[int],
                             batch_format: str = "numpy",
                             drop_last: bool = False,
                             local_shuffle_buffer_size: Optional[int] = None,
                             local_shuffle_seed: Optional[int] = None):
    """Core batching loop over an iterator of Blocks."""
    buf: deque = deque()
    shuffle_rows: List[Block] = []
    rng = np.random.default_rng(local_shuffle_seed)

    def emit(block: Block):
        return BlockAccessor(block).to_batch(batch_format)

    for block in block_iter:
        if block.num_rows == 0:
            continue
        if local_shuffle_buffer_size:
            shuffle_rows.append(block)
            have = sum(b.num_rows for b in shuffle_rows)
            if have >= local_shuffle_buffer_size:
                merged = BlockAccessor.concat(shuffle_rows)
                merged = BlockAccessor(merged).random_shuffle(
                    int(rng.integers(0, 2**31)))
                shuffle_rows = []
                buf.append(merged)
        else:
            buf.append(block)
        while True:
            size = batch_size or (buf[0].num_rows if buf else 0)
            if size == 0:
                break
            batch = _slice_concat(buf, size)
            if batch is None:
                break
            yield emit(batch)

    if shuffle_rows:
        merged = BlockAccessor.concat(shuffle_rows)
        merged = BlockAccessor(merged).random_shuffle(
            int(rng.integers(0, 2**31)))
        buf.append(merged)
    # Tail.
    while buf:
        remaining = sum(b.num_rows for b in buf)
        if remaining == 0:
            break
        size = batch_size or remaining
        if remaining >= size:
            yield emit(_slice_concat(buf, size))
        else:
            if not drop_last:
                yield emit(_slice_concat(buf, remaining))
            break


class DataIterator:
    """One consumer's view of a dataset (reference: data/iterator.py)."""

    def _block_iter(self) -> Iterator[Block]:
        raise NotImplementedError

    def iter_rows(self):
        for block in self._block_iter():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 1):
        return iter_batches_from_blocks(
            self._block_iter(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu", **kw):
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes is not None:
                    t = t.to(dtypes[k] if isinstance(dtypes, dict) else dtypes)
                out[k] = t.to(device)
            yield out

    def iter_device_batches(self, *, batch_size: Optional[int] = 256,
                            sharding=None, dtypes=None, drop_last: bool = True,
                            prefetch: int = 2, **kw):
        """Yield batches as jax.Arrays on device, with a small host-side
        prefetch queue so host->HBM transfer overlaps compute
        (TPU-native equivalent of iter_torch_batches+pin_memory)."""
        import jax
        import jax.numpy as jnp

        def to_device(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                arr = jnp.asarray(v, dtype=dtypes.get(k) if isinstance(
                    dtypes, dict) else dtypes)
                if sharding is not None:
                    arr = jax.device_put(arr, sharding)
                out[k] = arr
            return out

        queue: deque = deque()
        it = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                               drop_last=drop_last, **kw)
        for batch in it:
            queue.append(to_device(batch))  # async dispatch
            if len(queue) > prefetch:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    def materialize_blocks(self) -> List[Block]:
        return list(self._block_iter())


class _ExecutionIterator(DataIterator):
    """Iterates a dataset by (re-)executing its plan each epoch."""

    def __init__(self, dataset):
        self._dataset = dataset

    def _block_iter(self):
        for bundle in self._dataset._execute_stream():
            yield ray_tpu.get(bundle.block_ref)


class _SplitCoordinator:
    """Actor distributing one execution's blocks to n consumers.

    reference: data/_internal/execution/operators/output_splitter.py via
    Dataset.streaming_split: each output split pulls the next block for
    its index; `equal=True` balances rows by splitting blocks.
    """

    def __init__(self, plan_blob: bytes, n: int, equal: bool):
        import cloudpickle
        self._make_stream = cloudpickle.loads(plan_blob)
        self.n = n
        self.equal = equal
        self.lock = locktrace.traced_lock("data.iterator")
        self.queues: List[deque] = [deque() for _ in range(n)]
        self.stream = None
        self.done = False
        self.epoch = -1
        self.rr = 0  # round-robin cursor

    def start_epoch(self, epoch: int):
        with self.lock:
            if epoch > self.epoch:
                self.epoch = epoch
                self.stream = self._make_stream()
                self.done = False
                self.queues = [deque() for _ in range(self.n)]
                self.rr = 0
        return self.epoch

    def _pump(self):
        """Pull one bundle from the stream into the emptiest queue."""
        try:
            bundle = next(self.stream)
        except StopIteration:
            self.done = True
            return False
        i = self.rr % self.n
        self.rr += 1
        self.queues[i].append(bundle.block_ref)
        return True

    def get_next(self, split_idx: int):
        """Returns a block ref, or None when the epoch is exhausted."""
        with self.lock:
            while not self.queues[split_idx] and not self.done:
                self._pump()
            if self.queues[split_idx]:
                return self.queues[split_idx].popleft()
            return None


class _SplitIterator(DataIterator):
    def __init__(self, coordinator, split_idx: int, n: int):
        self._coord = coordinator
        self._idx = split_idx
        self._n = n
        self._epoch = 0

    def _block_iter(self):
        ray_tpu.get(self._coord.start_epoch.remote(self._epoch))
        self._epoch += 1
        while True:
            ref = ray_tpu.get(self._coord.get_next.remote(self._idx))
            if ref is None:
                return
            yield ray_tpu.get(ref)


def make_streaming_split(dataset, n: int, equal: bool) -> List[DataIterator]:
    import cloudpickle

    ds = dataset

    def make_stream():
        return ds._execute_stream()

    blob = cloudpickle.dumps(make_stream)
    coord_cls = ray_tpu.remote(num_cpus=0.5)(_SplitCoordinator)
    coord = coord_cls.remote(blob, n, equal)
    return [_SplitIterator(coord, i, n) for i in range(n)]
