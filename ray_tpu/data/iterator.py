"""DataIterator: batch iteration and streaming_split.

reference: python/ray/data/iterator.py:106 (iter_batches with
batch_size/format/local shuffle, iter_torch_batches) and
dataset.py:1853 streaming_split — n consumers fed from one execution via
a coordinator actor (reference: _internal/execution/streaming_split
output_splitter.py). Device feeding for TPU: `iter_device_batches`
yields jax arrays staged host->HBM with double buffering.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time

from ray_tpu.devtools import locktrace
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util import metrics

PREFETCH_WAIT = metrics.Histogram(
    "ray_tpu_data_prefetch_wait_seconds",
    "Time the consumer blocked waiting for the next prefetched batch "
    "(non-trivial values mean the trainer stalled on data)")

_SENTINEL = object()


class _PrefetchingIter:
    """Pulls `source` on a background daemon thread through a bounded
    queue, so production (block fetch, batching, device staging) overlaps
    the consumer's work. Depth bounds memory; consumer wait times are
    flushed to the prefetch-wait histogram via one record_batch per
    window (never one RPC per batch)."""

    _FLUSH_EVERY = 32

    def __init__(self, source: Iterator, depth: int):
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._waits: List[float] = []
        # Observability for tests/bench: when the producer finished, and
        # total seconds the consumer spent blocked on the queue.
        self.producer_done_time: Optional[float] = None
        self.wait_seconds_total = 0.0
        self._done = False
        self._thread = threading.Thread(
            target=self._produce, args=(source,),
            name="rtpu-data-prefetch", daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        rec = _flight.RECORDER
        t0_ns = rec.clock() if rec is not None else 0
        blocked = False
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                if blocked and rec is not None:
                    # producer outran the consumer: queue-full stall
                    rec.record("prefetch", "producer_wait", t0_ns,
                               rec.clock() - t0_ns, None)
                return True
            except queue_mod.Full:
                blocked = True
                continue
        return False

    def _produce(self, source: Iterator) -> None:
        try:
            for item in source:
                if not self._put((item,)):
                    return  # consumer went away
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._exc = e
        finally:
            self.producer_done_time = time.monotonic()
            self._put(_SENTINEL)

    def _flush_waits(self) -> None:
        waits, self._waits = self._waits, []
        if waits:
            metrics.record_batch([
                ("histogram", "ray_tpu_data_prefetch_wait_seconds", None,
                 w, PREFETCH_WAIT._boundaries) for w in waits])

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        rec = _flight.RECORDER
        t0_ns = rec.clock() if rec is not None else 0
        t0 = time.monotonic()
        item = self._queue.get()
        wait = time.monotonic() - t0
        if rec is not None:
            rec.record("prefetch", "consumer_wait", t0_ns,
                       rec.clock() - t0_ns, None)
        self.wait_seconds_total += wait
        self._waits.append(wait)
        if len(self._waits) >= self._FLUSH_EVERY or item is _SENTINEL:
            self._flush_waits()
        if item is _SENTINEL:
            self._done = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item[0]

    def close(self) -> None:
        self._stop.set()
        self._flush_waits()

    def __del__(self):
        # Abandoned mid-iteration (e.g. an early break): unblock the
        # producer so its thread exits instead of spinning on put().
        self._stop.set()


def _slice_concat(blocks: deque, batch_size: int) -> Optional[Block]:
    """Pop exactly batch_size rows off the front of `blocks` (concat as
    needed); returns None if fewer rows are buffered."""
    have = sum(b.num_rows for b in blocks)
    if have < batch_size:
        return None
    parts, need = [], batch_size
    while need > 0:
        b = blocks.popleft()
        if b.num_rows <= need:
            parts.append(b)
            need -= b.num_rows
        else:
            parts.append(BlockAccessor(b).slice(0, need))
            blocks.appendleft(BlockAccessor(b).slice(need, b.num_rows))
            need = 0
    return BlockAccessor.concat(parts)


def iter_batches_from_blocks(block_iter, *, batch_size: Optional[int],
                             batch_format: str = "numpy",
                             drop_last: bool = False,
                             local_shuffle_buffer_size: Optional[int] = None,
                             local_shuffle_seed: Optional[int] = None):
    """Core batching loop over an iterator of Blocks."""
    buf: deque = deque()
    shuffle_rows: List[Block] = []
    rng = np.random.default_rng(local_shuffle_seed)

    def emit(block: Block):
        return BlockAccessor(block).to_batch(batch_format)

    for block in block_iter:
        if block.num_rows == 0:
            continue
        if local_shuffle_buffer_size:
            shuffle_rows.append(block)
            have = sum(b.num_rows for b in shuffle_rows)
            if have >= local_shuffle_buffer_size:
                merged = BlockAccessor.concat(shuffle_rows)
                merged = BlockAccessor(merged).random_shuffle(
                    int(rng.integers(0, 2**31)))
                shuffle_rows = []
                buf.append(merged)
        else:
            buf.append(block)
        while True:
            size = batch_size or (buf[0].num_rows if buf else 0)
            if size == 0:
                break
            batch = _slice_concat(buf, size)
            if batch is None:
                break
            yield emit(batch)

    if shuffle_rows:
        merged = BlockAccessor.concat(shuffle_rows)
        merged = BlockAccessor(merged).random_shuffle(
            int(rng.integers(0, 2**31)))
        buf.append(merged)
    # Tail.
    while buf:
        remaining = sum(b.num_rows for b in buf)
        if remaining == 0:
            break
        size = batch_size or remaining
        if remaining >= size:
            yield emit(_slice_concat(buf, size))
        else:
            if not drop_last:
                yield emit(_slice_concat(buf, remaining))
            break


class DataIterator:
    """One consumer's view of a dataset (reference: data/iterator.py)."""

    def _block_iter(self) -> Iterator[Block]:
        raise NotImplementedError

    def iter_rows(self):
        for block in self._block_iter():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: Optional[int] = None):
        """Iterate host batches. With ``prefetch_batches`` > 0 (default:
        DataContext.iterator_prefetch_batches) block fetch + batching run
        on a background thread, `prefetch_batches` deep; 0 disables."""
        batches = iter_batches_from_blocks(
            self._block_iter(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)
        if prefetch_batches is None:
            prefetch_batches = \
                DataContext.get_current().iterator_prefetch_batches
        if prefetch_batches and prefetch_batches > 0:
            return _PrefetchingIter(batches, prefetch_batches)
        return batches

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu", **kw):
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes is not None:
                    t = t.to(dtypes[k] if isinstance(dtypes, dict) else dtypes)
                out[k] = t.to(device)
            yield out

    def iter_device_batches(self, *, batch_size: Optional[int] = 256,
                            sharding=None, dtypes=None, drop_last: bool = True,
                            prefetch: Optional[int] = None, **kw):
        """Yield batches as jax.Arrays on device, double-buffered: a
        producer thread runs batching AND the `device_put` dispatch, so
        host->HBM transfer of batch n+1..n+prefetch overlaps the
        consumer's compute on batch n (TPU-native equivalent of
        iter_torch_batches+pin_memory). The old implementation dispatched
        device_put on the consumer's critical path."""
        import jax
        import jax.numpy as jnp

        if prefetch is None:
            prefetch = DataContext.get_current().device_prefetch_batches
        prefetch = max(1, prefetch)

        def to_device(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                arr = jnp.asarray(v, dtype=dtypes.get(k) if isinstance(
                    dtypes, dict) else dtypes)
                if sharding is not None:
                    arr = jax.device_put(arr, sharding)
                out[k] = arr
            return out

        # Host batching stays synchronous HERE (prefetch_batches=0) —
        # the device-staging thread below is the producer; stacking a
        # second queue between them would only add latency.
        it = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                               drop_last=drop_last,
                               prefetch_batches=0, **kw)
        staged = _PrefetchingIter((to_device(b) for b in it), prefetch)
        self._last_device_iter = staged  # overlap stats for tests/bench
        return staged

    def materialize_blocks(self) -> List[Block]:
        return list(self._block_iter())


class _ExecutionIterator(DataIterator):
    """Iterates a dataset by (re-)executing its plan each epoch."""

    def __init__(self, dataset):
        self._dataset = dataset

    def _block_iter(self):
        for bundle in self._dataset._execute_stream():
            yield ray_tpu.get(bundle.block_ref)


class _SplitCoordinator:
    """Actor distributing one execution's blocks to n consumers.

    reference: data/_internal/execution/operators/output_splitter.py via
    Dataset.streaming_split: each output split pulls the next block for
    its index; `equal=True` balances rows by splitting blocks.
    """

    def __init__(self, plan_blob: bytes, n: int, equal: bool):
        import cloudpickle
        self._make_stream = cloudpickle.loads(plan_blob)
        self.n = n
        self.equal = equal
        self.lock = locktrace.traced_lock("data.iterator")
        self.queues: List[deque] = [deque() for _ in range(n)]
        self.stream = None
        self.done = False
        self.epoch = -1
        self.rr = 0  # round-robin cursor

    def start_epoch(self, epoch: int):
        with self.lock:
            if epoch > self.epoch:
                self.epoch = epoch
                self.stream = self._make_stream()
                self.done = False
                self.queues = [deque() for _ in range(self.n)]
                self.rr = 0
        return self.epoch

    def _pump(self):
        """Pull one bundle from the stream into the emptiest queue."""
        try:
            bundle = next(self.stream)
        except StopIteration:
            self.done = True
            return False
        i = self.rr % self.n
        self.rr += 1
        self.queues[i].append(bundle.block_ref)
        return True

    def get_next(self, split_idx: int):
        """Returns a block ref, or None when the epoch is exhausted."""
        with self.lock:
            while not self.queues[split_idx] and not self.done:
                self._pump()
            if self.queues[split_idx]:
                return self.queues[split_idx].popleft()
            return None

    def get_next_many(self, split_idx: int, k: int):
        """Up to ``k`` block refs in one RPC (empty list = exhausted) —
        halves the per-block round-trips of the get_next protocol."""
        out = []
        with self.lock:
            while len(out) < k:
                while not self.queues[split_idx] and not self.done:
                    self._pump()
                if not self.queues[split_idx]:
                    break
                out.append(self.queues[split_idx].popleft())
        return out


class _SplitIterator(DataIterator):
    def __init__(self, coordinator, split_idx: int, n: int):
        self._coord = coordinator
        self._idx = split_idx
        self._n = n
        self._epoch = 0

    _FETCH_BATCH = 4

    def _block_iter(self):
        ray_tpu.get(self._coord.start_epoch.remote(self._epoch))
        self._epoch += 1
        while True:
            refs = ray_tpu.get(
                self._coord.get_next_many.remote(self._idx,
                                                 self._FETCH_BATCH))
            if not refs:
                return
            # one batched fetch for the whole window of blocks
            for block in ray_tpu.get(list(refs)):
                yield block


def make_streaming_split(dataset, n: int, equal: bool) -> List[DataIterator]:
    import cloudpickle

    ds = dataset

    def make_stream():
        return ds._execute_stream()

    blob = cloudpickle.dumps(make_stream)
    coord_cls = ray_tpu.remote(num_cpus=0.5)(_SplitCoordinator)
    coord = coord_cls.remote(blob, n, equal)
    return [_SplitIterator(coord, i, n) for i in range(n)]
