"""Physical operators + the streaming executor.

reference: python/ray/data/_internal/execution/streaming_executor.py:64
(execute:152, _scheduling_loop_step:451) and
streaming_executor_state.py:739 (select_operator_to_run); operators under
data/_internal/execution/operators/. Here the executor is a pull-based
generator: blocks flow as ObjectRefs between operators, each map operator
keeps a bounded task pool (backpressure = bounded in-flight tasks plus a
bounded output queue), and all-to-all ops are barriers that orchestrate
shuffle stages with num_returns=N tasks.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.data.context import DataContext
from ray_tpu.data.transforms import MapTransform, apply_transform_chain
from ray_tpu.util import metrics

logger = logging.getLogger(__name__)

SHUFFLE_BYTES = metrics.Counter(
    "ray_tpu_data_shuffle_bytes_total",
    "Bytes entering shuffle map tasks / leaving reduce tasks",
    tag_keys=("stage",))
BLOCKS_IN_FLIGHT = metrics.Gauge(
    "ray_tpu_data_blocks_in_flight",
    "Blocks buffered or being produced by the streaming executor")

# Flush the executor's locally-aggregated metric updates every this many
# scheduling steps (one record_batch RPC instead of one per update).
_METRIC_FLUSH_STEPS = 32

# ---------------------------------------------------------------------------
# Remote task bodies (module-level so they pickle by value once).
# ---------------------------------------------------------------------------


def _meta(block: Block) -> BlockMetadata:
    return BlockAccessor(block).metadata()


def _map_task(transforms: List[MapTransform], block: Block):
    out = apply_transform_chain(block, transforms)
    return out, _meta(out)


def _read_task(read_fn: Callable[[], Any]):
    result = read_fn()
    blocks: List[Block] = []
    if isinstance(result, pa.Table):
        blocks = [result]
    else:
        blocks = [b if isinstance(b, pa.Table) else BlockAccessor.from_batch(b)
                  for b in result]
    out = BlockAccessor.concat(blocks) if blocks else pa.table({})
    return out, _meta(out)


def _slice_task(block: Block, start: int, end: int):
    out = BlockAccessor(block).slice(start, end)
    return out, _meta(out)


def _concat_task(*blocks: Block):
    out = BlockAccessor.concat(list(blocks))
    return out, _meta(out)


def _shuffle_map_task(block: Block, n: int, seed):
    if n == 1:
        return block
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n, size=block.num_rows)
    return tuple(BlockAccessor(block).take_rows(np.nonzero(assign == i)[0])
                 for i in range(n))


def _shuffle_reduce_task(seed, *shards: Block):
    out = BlockAccessor.concat(list(shards))
    out = BlockAccessor(out).random_shuffle(seed)
    return out, _meta(out)


def _sort_sample_task(block: Block, keys: List[str]):
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return []
    idx = np.linspace(0, n - 1, num=min(n, 64)).astype(np.int64)
    sampled = acc.take_rows(idx)
    cols = [sampled.column(k).to_pylist() for k in keys]
    return list(zip(*cols))


def _sort_partition_task(block: Block, keys: List[str], boundaries,
                         descending: bool, n: int):
    if block.num_rows == 0 and not block.column_names:
        # schema-less empty block (e.g. a row-map over zero rows):
        # nothing to sort, and sort_by on missing keys would raise
        empty = block
        return empty if n == 1 else tuple(empty for _ in range(n))
    acc = BlockAccessor(block)
    sorted_block = acc.sort(keys, descending)
    if n == 1:
        return sorted_block
    cols = [sorted_block.column(k).to_pylist() for k in keys]
    key_tuples = list(zip(*cols))
    import bisect
    # Partition assignment is always on the ascending boundaries; a
    # descending sort just emits partitions in reverse order.
    parts: List[List[int]] = [[] for _ in range(n)]
    for i, kt in enumerate(key_tuples):
        j = bisect.bisect_right(boundaries, kt)
        parts[min(j, n - 1)].append(i)
    sacc = BlockAccessor(sorted_block)
    return tuple(sacc.take_rows(np.asarray(p, dtype=np.int64))
                 for p in parts)


def _merge_sorted_task(keys: List[str], descending: bool, *parts: Block):
    out = BlockAccessor.concat(list(parts))
    if out.num_rows:
        out = BlockAccessor(out).sort(keys, descending)
    return out, _meta(out)


def _groupby_map_task(block: Block, keys: List[str], n: int):
    if n == 1:
        return block
    if not keys:  # global aggregation: everything to partition 0
        return (block,) + tuple(block.schema.empty_table()
                                for _ in range(n - 1))
    import zlib
    cols = [block.column(k).to_pylist() for k in keys]
    # Stable cross-process hash: Python's hash() is salted per process,
    # which would scatter one key over several partitions.
    hashes = np.asarray([zlib.crc32(repr(t).encode()) % n
                         for t in zip(*cols)], dtype=np.int64)
    acc = BlockAccessor(block)
    return tuple(acc.take_rows(np.nonzero(hashes == i)[0])
                 for i in range(n))


def _groupby_reduce_task(keys: List[str], aggs, *parts: Block):
    from ray_tpu.data.aggregate import aggregate_block
    merged = BlockAccessor.concat(list(parts))
    out = aggregate_block(merged, keys, aggs)
    return out, _meta(out)


def _zip_task(left: Block, right: Block):
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out_name = name if name not in cols else name + "_1"
        cols[out_name] = right.column(name)
    out = pa.table(cols)
    return out, _meta(out)


_JOIN_TYPES = {
    "inner": "inner",
    "left": "left outer",
    "right": "right outer",
    "outer": "full outer",
}


def _join_partition_task(keys: List[str], how: str, n_left: int,
                         *parts: Block):
    """Join one hash partition: the first ``n_left`` parts are the left
    side's shards, the rest the right's (reference analog: hash_shuffle
    join reducers, data/_internal/execution/operators/join.py)."""
    left = BlockAccessor.concat(list(parts[:n_left]))
    right = BlockAccessor.concat(list(parts[n_left:]))
    out = left.join(right, keys=keys, join_type=_JOIN_TYPES[how],
                    right_suffix="_r")
    return out, _meta(out)


def _write_task(write_fn: Callable[[Block], Any], block: Block):
    result = write_fn(block)
    out = pa.table({"write_result": pa.array([result], type=pa.string())
                    if isinstance(result, str) else pa.array([1])})
    return out, _meta(out)


# ---------------------------------------------------------------------------
# Bundles and operator state
# ---------------------------------------------------------------------------


@dataclass
class RefBundle:
    """One block ref + its metadata (reference:
    data/_internal/execution/interfaces/ref_bundle.py).

    `order` is the bundle's position in its producing op's output
    sequence; maps preserve it, barriers sort by it, and the sink yields
    in order — deterministic output without sacrificing out-of-order
    task completion."""

    block_ref: Any
    metadata: BlockMetadata
    order: int = 0


class PhysicalOp:
    def __init__(self, name: str, inputs: List["PhysicalOp"]):
        self.name = name
        self.inputs = inputs

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class InputDataOp(PhysicalOp):
    def __init__(self, bundles: List[RefBundle]):
        super().__init__("InputData", [])
        self.bundles = bundles


class ReadPhysicalOp(PhysicalOp):
    def __init__(self, read_tasks: List[Callable], name: str = "Read"):
        super().__init__(name, [])
        self.read_tasks = read_tasks


class MapPhysicalOp(PhysicalOp):
    def __init__(self, transforms: List[MapTransform], input_op: PhysicalOp,
                 *, compute: str = "tasks", concurrency: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 name: str = "Map"):
        super().__init__(name, [input_op])
        self.transforms = transforms
        self.compute = compute
        self.concurrency = concurrency
        self.resources = resources or {}


class AllToAllPhysicalOp(PhysicalOp):
    def __init__(self, kind: str, input_op: PhysicalOp, *,
                 num_outputs: Optional[int] = None, key=None,
                 descending: bool = False, seed=None, aggs=None,
                 name: Optional[str] = None):
        super().__init__(name or kind, [input_op])
        self.kind = kind
        self.num_outputs = num_outputs
        self.key = key
        self.descending = descending
        self.seed = seed
        self.aggs = aggs or []


class LimitPhysicalOp(PhysicalOp):
    def __init__(self, input_op: PhysicalOp, limit: int):
        super().__init__(f"Limit[{limit}]", [input_op])
        self.limit = limit


class UnionPhysicalOp(PhysicalOp):
    def __init__(self, inputs: List[PhysicalOp]):
        super().__init__("Union", inputs)


class JoinPhysicalOp(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp, *,
                 on: List[str], how: str = "inner",
                 num_partitions: Optional[int] = None):
        super().__init__(f"Join({how})", [left, right])
        if how not in _JOIN_TYPES:
            raise ValueError(
                f"unknown join type {how!r}; one of {list(_JOIN_TYPES)}")
        self.on = list(on)
        self.how = how
        self.num_partitions = num_partitions


class ZipPhysicalOp(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp):
        super().__init__("Zip", [left, right])


class WritePhysicalOp(PhysicalOp):
    def __init__(self, write_fn: Callable, input_op: PhysicalOp,
                 name: str = "Write"):
        super().__init__(name, [input_op])
        self.write_fn = write_fn


# ---------------------------------------------------------------------------
# Actor pool for compute="actors" map operators
# ---------------------------------------------------------------------------


class _MapWorker:
    """Long-lived worker for actor-based map_batches
    (reference: data/_internal/execution/operators/actor_pool_map_operator.py).
    """

    def ready(self):
        return "ok"

    def map(self, transforms, block):
        return _map_task(transforms, block)


class _ActorPool:
    """Autoscaling actor pool (reference: data/_internal/execution/
    autoscaler/actor_autoscaler — pools scale within [min, max] on
    utilization). ``pick`` grows the pool when every actor is busy;
    ``maybe_scale_down`` reaps actors idle beyond a grace period."""

    IDLE_REAP_S = 10.0

    def __init__(self, size, resources: Dict[str, float]):
        import time as _time
        if isinstance(size, (tuple, list)):
            self.min_size, self.max_size = int(size[0]), int(size[1])
        else:
            self.min_size = self.max_size = int(size)
        if not 1 <= self.min_size <= self.max_size:
            raise ValueError(f"bad actor pool bounds {size!r}")
        self._cpu_per_actor = resources.get("CPU", 1)
        self._extra_resources = {k: v for k, v in resources.items()
                                 if k != "CPU"}
        self._actor_cls = ray_tpu.remote(
            num_cpus=self._cpu_per_actor,
            resources=self._extra_resources or None,
        )(_MapWorker)
        self.actors: Dict[int, Any] = {}
        self.load: Dict[int, int] = {}
        self._idle_since: Dict[int, float] = {}
        self._next_id = 0
        self._time = _time
        for _ in range(self.min_size):
            self._add_actor()

    def _add_actor(self) -> int:
        i = self._next_id
        self._next_id += 1
        self.actors[i] = self._actor_cls.remote()
        self.load[i] = 0
        self._idle_since[i] = self._time.monotonic()
        return i

    def _cluster_has_room(self) -> bool:
        """Only scale up when the cluster can actually place another
        actor — an unplaceable actor would buffer its bundles behind a
        never-ALIVE creation forever."""
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001
            return False
        if avail.get("CPU", 0.0) < self._cpu_per_actor:
            return False
        return all(avail.get(k, 0.0) >= v
                   for k, v in self._extra_resources.items())

    def pick(self) -> Tuple[int, Any]:
        i = min(self.load, key=lambda k: self.load[k])
        if (self.load[i] > 0 and len(self.actors) < self.max_size
                and self._cluster_has_room()):
            i = self._add_actor()  # all busy + capacity: scale up
        self.load[i] += 1
        self._idle_since.pop(i, None)
        return i, self.actors[i]

    def release(self, i: int):
        if i not in self.load:
            return  # reaped while its last task was in flight
        self.load[i] -= 1
        if self.load[i] == 0:
            self._idle_since[i] = self._time.monotonic()

    def maybe_scale_down(self) -> None:
        if len(self.actors) <= self.min_size:
            return
        now = self._time.monotonic()
        for i, since in list(self._idle_since.items()):
            if len(self.actors) <= self.min_size:
                return
            if self.load.get(i) == 0 and now - since > self.IDLE_REAP_S:
                actor = self.actors.pop(i)
                self.load.pop(i, None)
                self._idle_since.pop(i, None)
                try:
                    ray_tpu.kill(actor)
                except Exception:  # noqa: BLE001 — actor already dead
                    logger.debug("scale-down kill failed", exc_info=True)

    def shutdown(self):
        for a in self.actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — actor already dead
                logger.debug("pool shutdown kill failed", exc_info=True)


class _OpState:
    def __init__(self, op: PhysicalOp, ctx: DataContext):
        self.op = op
        self.inqueues: List[deque] = [deque() for _ in op.inputs]
        self.outqueue: deque = deque()
        self.inputs_done: List[bool] = [False] * len(op.inputs)
        self.started = False
        self.finished = False
        self.in_flight = 0
        self.rows_emitted = 0  # for Limit
        self.pending_reads: deque = deque()
        self.actor_pool: Optional[_ActorPool] = None
        self.ctx = ctx
        self.emit_counter = 0  # fresh order indices (Union)
        if isinstance(op, ReadPhysicalOp):
            self.pending_reads.extend(enumerate(op.read_tasks))

    def all_inputs_done(self) -> bool:
        return all(self.inputs_done) and all(not q for q in self.inqueues)

    def has_input(self) -> bool:
        if isinstance(self.op, ReadPhysicalOp):
            return bool(self.pending_reads)
        return any(q for q in self.inqueues)

    def under_limits(self) -> bool:
        return (self.in_flight < self.ctx.max_tasks_in_flight_per_op
                and len(self.outqueue) < self.ctx.max_blocks_in_op_output_queue)


class ResourceManager:
    """Global memory accounting + source backpressure for one stream
    (reference: data/_internal/execution/resource_manager.py and the
    backpressure policies under execution/backpressure_policy/ — here
    two policies are built in: a per-op concurrency/output-queue cap
    (_OpState.under_limits) and this global queued-bytes budget that
    pauses sources while the pipeline holds too much data)."""

    def __init__(self, states: Dict[int, "_OpState"], ctx: DataContext):
        self._states = states
        from ray_tpu.core.config import get_config
        self.budget = (ctx.memory_budget_bytes
                       or get_config().object_store_memory // 2)
        self.peak_queued_bytes = 0

    def queued_bytes(self) -> int:
        total = 0
        for st in self._states.values():
            for q in (st.outqueue, *st.inqueues):
                for bundle in q:
                    total += bundle.metadata.size_bytes or 0
        if total > self.peak_queued_bytes:
            self.peak_queued_bytes = total
        return total

    def refresh(self) -> None:
        """Recompute once per scheduling step — a full queue walk per
        dispatch attempt would be O(blocks x queued) over a run; the
        within-step staleness only adds the same slack class as
        in-flight task outputs."""
        self._cached = self.queued_bytes()

    def allow_source_dispatch(self) -> bool:
        cached = getattr(self, "_cached", None)
        if cached is None:
            cached = self.queued_bytes()
        return cached < self.budget


class _ShuffleState:
    """Per-op state for the pipelined random_shuffle (reference analog:
    push-based shuffle in data/_internal/planner/exchange/ — here map
    outputs feed fixed fan-in reduce *waves* so reducers start while maps
    are still running).

    Reducer ``i``'s wave ``w`` consumes the shards scattered to it by map
    inputs ``[w*fanin, (w+1)*fanin)`` (by bundle order, so wave
    composition is deterministic no matter which tasks finish first) and
    emits output order ``w * n_out + i`` — dense, which keeps the sink's
    in-order hold-back working unchanged. A full-size wave can launch as
    soon as its members' shards exist; the final partial wave waits until
    the total input count is known."""

    def __init__(self, op: "AllToAllPhysicalOp", ctx: DataContext):
        self.n_out = max(1, op.num_outputs or ctx.shuffle_num_reducers
                         or ctx.min_parallelism)
        self.fanin = max(1, ctx.shuffle_reduce_fanin)
        # Cap on map shard-sets buffered + being produced; clamped up to
        # the fan-in so a wave can always assemble without deadlock.
        self.window = max(ctx.max_shuffle_blocks_in_flight, self.fanin)
        self.shards: Dict[int, Tuple] = {}  # map order j -> n_out shard refs
        self.maps_in_flight = 0
        self.maps_dispatched = 0
        self.maps_done = 0
        self.n_maps: Optional[int] = None  # known once input is exhausted
        self.reduce_wave = 0    # next (wave, reducer) to launch
        self.reduce_i = 0
        self.reduces_in_flight = 0
        self.outputs_emitted = 0
        # Streaming proof + bound proof (read by tests/bench):
        self.first_output_maps_done: Optional[int] = None
        self.peak_in_flight_blocks = 0
        self.bytes_map_in = 0
        self.bytes_reduce_out = 0
        # flight-recorder launch stamps: ("map"|"reduce", order) -> ns
        self.flight_t0: Dict[Tuple[str, int], int] = {}

    def note_in_flight(self) -> int:
        cur = (len(self.shards) + self.maps_in_flight
               + self.reduces_in_flight)
        if cur > self.peak_in_flight_blocks:
            self.peak_in_flight_blocks = cur
        return cur

    def wave_span(self, w: int) -> Tuple[int, Optional[int]]:
        lo = w * self.fanin
        if self.n_maps is not None:
            return lo, min(lo + self.fanin, self.n_maps)
        return lo, lo + self.fanin  # full-size wave assumed until EOS

    def all_waves_launched(self) -> bool:
        return (self.n_maps is not None
                and self.reduce_wave * self.fanin >= self.n_maps)


class StreamingExecutor:
    """Executes a physical DAG, yielding output RefBundles as they become
    available. Pull-based: work only advances while the consumer iterates,
    and bounded queues give memory backpressure."""

    def __init__(self, dag: PhysicalOp, ctx: Optional[DataContext] = None):
        self.dag = dag
        self.ctx = ctx or DataContext.get_current()
        self.states: Dict[int, _OpState] = {}
        self.topo: List[PhysicalOp] = []
        self._build(dag)
        self.resource_manager = ResourceManager(self.states, self.ctx)
        # pending task ref -> tagged completion tuple:
        #   ("bundle", op, b_ref, actor_idx, order)   map/read/write
        #   ("shuffle_map", op, order, shard_refs)    scatter task
        #   ("shuffle_reduce", op, b_ref, order)      wave reduce
        self.pending: Dict[Any, Tuple] = {}
        self.shuffle_states: Dict[int, _ShuffleState] = {}
        self._metric_buf: List[Tuple] = []
        self._steps = 0

    # -- metrics ------------------------------------------------------
    def _metric(self, kind: str, name: str, tags, value) -> None:
        self._metric_buf.append((kind, name, tags, value, None))

    def _flush_metrics(self) -> None:
        in_flight = sum(st.in_flight for st in self.states.values())
        in_flight += sum(len(ss.shards) for ss in self.shuffle_states.values())
        self._metric(
            "gauge", "ray_tpu_data_blocks_in_flight", None, in_flight)
        buf, self._metric_buf = self._metric_buf, []
        metrics.record_batch(buf)

    def _build(self, op: PhysicalOp):
        if id(op) in self.states:
            return
        for inp in op.inputs:
            self._build(inp)
        self.states[id(op)] = _OpState(op, self.ctx)
        self.topo.append(op)

    # -- dispatch helpers --------------------------------------------
    def _remote_map(self, op: MapPhysicalOp):
        opts = {"num_returns": 2}
        if op.resources:
            cpus = op.resources.get("CPU")
            if cpus is not None:
                opts["num_cpus"] = cpus
            rest = {k: v for k, v in op.resources.items() if k != "CPU"}
            if rest:
                opts["resources"] = rest
        return ray_tpu.remote(**opts)(_map_task)

    def _dispatch(self, op: PhysicalOp, st: _OpState):
        if isinstance(op, ReadPhysicalOp):
            order, read_fn = st.pending_reads.popleft()
            b_ref, m_ref = ray_tpu.remote(num_returns=2)(_read_task).remote(read_fn)
            self.pending[m_ref] = ("bundle", op, b_ref, None, order)
            st.in_flight += 1
        elif isinstance(op, MapPhysicalOp):
            bundle: RefBundle = st.inqueues[0].popleft()
            if op.compute == "actors":
                if st.actor_pool is None:
                    size = op.concurrency or 2
                    st.actor_pool = _ActorPool(size, op.resources)
                idx, actor = st.actor_pool.pick()
                b_ref, m_ref = actor.map.options(num_returns=2).remote(
                    op.transforms, bundle.block_ref)
                self.pending[m_ref] = ("bundle", op, b_ref, idx, bundle.order)
            else:
                b_ref, m_ref = self._remote_map(op).remote(
                    op.transforms, bundle.block_ref)
                self.pending[m_ref] = ("bundle", op, b_ref, None, bundle.order)
            st.in_flight += 1
        elif isinstance(op, WritePhysicalOp):
            bundle = st.inqueues[0].popleft()
            b_ref, m_ref = ray_tpu.remote(num_returns=2)(_write_task).remote(
                op.write_fn, bundle.block_ref)
            self.pending[m_ref] = ("bundle", op, b_ref, None, bundle.order)
            st.in_flight += 1

    def _forward(self, op: PhysicalOp, bundle: RefBundle):
        """Push an output bundle to every consumer's inqueue."""
        for consumer in self.topo:
            for i, inp in enumerate(consumer.inputs):
                if inp is op:
                    self.states[id(consumer)].inqueues[i].append(bundle)

    def _mark_finished(self, op: PhysicalOp):
        st = self.states[id(op)]
        if st.finished:
            return
        st.finished = True
        if st.actor_pool is not None:
            st.actor_pool.shutdown()
            st.actor_pool = None
        for consumer in self.topo:
            for i, inp in enumerate(consumer.inputs):
                if inp is op:
                    self.states[id(consumer)].inputs_done[i] = True

    # -- barrier (all-to-all) execution ------------------------------
    def _run_all_to_all(self, op: AllToAllPhysicalOp, st: _OpState):
        bundles = sorted(st.inqueues[0], key=lambda b: b.order)
        st.inqueues[0].clear()
        refs = [b.block_ref for b in bundles]
        metas = [b.metadata for b in bundles]
        n_in = len(refs)
        n_out = op.num_outputs or max(n_in, 1)
        out: List[Tuple[Any, Any]] = []

        if n_in == 0:
            return

        if op.kind == "repartition":
            total_rows = sum(m.num_rows for m in metas)
            rows_per = [total_rows // n_out + (1 if i < total_rows % n_out else 0)
                        for i in range(n_out)]
            # global row ranges -> (block, start, end) slices per output
            slices: List[List[Any]] = [[] for _ in range(n_out)]
            block_starts = np.cumsum([0] + [m.num_rows for m in metas])
            out_starts = np.cumsum([0] + rows_per)
            for i in range(n_out):
                lo, hi = int(out_starts[i]), int(out_starts[i + 1])
                for j in range(n_in):
                    blo, bhi = int(block_starts[j]), int(block_starts[j + 1])
                    s, e = max(lo, blo), min(hi, bhi)
                    if s < e:
                        slices[i].append(
                            ray_tpu.remote(num_returns=2)(_slice_task).remote(
                                refs[j], s - blo, e - blo)[0])
            for i in range(n_out):
                b, m = ray_tpu.remote(num_returns=2)(_concat_task).remote(
                    *slices[i])
                out.append((b, m))
        elif op.kind == "sort":
            keys = [op.key] if isinstance(op.key, str) else list(op.key)
            samples = ray_tpu.get([
                ray_tpu.remote()(_sort_sample_task).remote(r, keys)
                for r in refs])
            flat = sorted([s for part in samples for s in part])
            if flat and n_out > 1:
                idx = np.linspace(0, len(flat) - 1, num=n_out + 1)[1:-1]
                boundaries = [flat[int(i)] for i in idx]
            else:
                boundaries = []
            n_parts = max(len(boundaries) + 1, 1)
            part_refs = []
            for r in refs:
                parts = ray_tpu.remote(num_returns=n_parts)(
                    _sort_partition_task).remote(
                        r, keys, boundaries, op.descending, n_parts)
                if n_parts == 1:
                    parts = [parts]
                part_refs.append(parts)
            order = range(n_parts - 1, -1, -1) if op.descending else range(n_parts)
            for i in order:
                b, m = ray_tpu.remote(num_returns=2)(_merge_sorted_task).remote(
                    keys, op.descending, *[part_refs[j][i] for j in range(n_in)])
                out.append((b, m))
        elif op.kind == "aggregate":
            keys = ([op.key] if isinstance(op.key, str)
                    else list(op.key) if op.key else [])
            n_parts = min(n_out, max(n_in, 1)) if keys else 1
            part_refs = []
            for r in refs:
                parts = ray_tpu.remote(num_returns=n_parts)(
                    _groupby_map_task).remote(r, keys, n_parts)
                if n_parts == 1:
                    parts = [parts]
                part_refs.append(parts)
            for i in range(n_parts):
                b, m = ray_tpu.remote(num_returns=2)(
                    _groupby_reduce_task).remote(
                        keys, op.aggs, *[part_refs[j][i] for j in range(n_in)])
                out.append((b, m))
        else:
            raise ValueError(f"unknown all-to-all kind {op.kind!r}")

        # One batched fetch for every output's metadata: N sequential
        # round-trips would serialize on the slowest reducer each time.
        metas = ray_tpu.get([m_ref for _b, m_ref in out])
        for i, ((b_ref, _m), meta) in enumerate(zip(out, metas)):
            st.outqueue.append(RefBundle(b_ref, meta, order=i))

    def _run_join(self, op: JoinPhysicalOp, st: _OpState):
        """Hash-partition both sides on the join keys, join partitions
        independently (barrier, like the reference's hash-shuffle join)."""
        left = sorted(st.inqueues[0], key=lambda b: b.order)
        st.inqueues[0].clear()
        right = sorted(st.inqueues[1], key=lambda b: b.order)
        st.inqueues[1].clear()
        if not left or not right:
            # empty result cases need no schema: inner always, and an
            # outer-preserved side that is itself empty
            if (op.how == "inner" or (not left and not right)
                    or (op.how == "left" and not left)
                    or (op.how == "right" and not right)):
                return
            raise ValueError(
                f"cannot {op.how}-join against an empty dataset: the "
                "empty side's schema is unknown (materialize it with a "
                "schema or use an inner join)")
        n_parts = op.num_partitions or max(len(left), len(right))
        part_refs = []  # per input block: list of n_parts shard refs
        for bundles in (left, right):
            for b in bundles:
                shards = ray_tpu.remote(num_returns=n_parts)(
                    _groupby_map_task).remote(b.block_ref, op.on, n_parts)
                if n_parts == 1:
                    shards = [shards]
                part_refs.append(shards)
        n_left = len(left)
        # launch every partition's join first, then collect metas — a
        # get() inside the launch loop would serialize the reducers
        pairs = [
            ray_tpu.remote(num_returns=2)(_join_partition_task).remote(
                op.on, op.how, n_left,
                *[part_refs[j][i] for j in range(len(part_refs))])
            for i in range(n_parts)
        ]
        metas = ray_tpu.get([m for _b, m in pairs])
        order = 0
        for (b, _m), meta in zip(pairs, metas):
            if meta.num_rows == 0:
                continue  # keys may hash to few partitions; don't emit
                # schema-losing empty blocks downstream
            st.outqueue.append(RefBundle(b, meta, order=order))
            order += 1
        if order == 0:
            # Entirely-empty result: keep ONE empty partition so the
            # joined schema survives — otherwise a downstream join sees
            # a zero-bundle side and can only fail with the
            # unknown-schema error above.
            st.outqueue.append(RefBundle(pairs[0][0], metas[0], order=0))

    def _run_zip(self, op: ZipPhysicalOp, st: _OpState):
        left = sorted(st.inqueues[0], key=lambda b: b.order)
        st.inqueues[0].clear()
        right = sorted(st.inqueues[1], key=lambda b: b.order)
        st.inqueues[1].clear()
        lrows = [b.metadata.num_rows for b in left]
        # Repartition right to match left's row layout, then zip blockwise.
        right_refs = [b.block_ref for b in right]
        rstarts = np.cumsum([0] + [b.metadata.num_rows for b in right])
        lstarts = np.cumsum([0] + lrows)
        pairs = []
        for i in range(len(left)):
            lo, hi = int(lstarts[i]), int(lstarts[i + 1])
            parts = []
            for j in range(len(right)):
                blo, bhi = int(rstarts[j]), int(rstarts[j + 1])
                s, e = max(lo, blo), min(hi, bhi)
                if s < e:
                    parts.append(ray_tpu.remote(num_returns=2)(
                        _slice_task).remote(right_refs[j], s - blo, e - blo)[0])
            rblock = ray_tpu.remote(num_returns=2)(_concat_task).remote(*parts)[0]
            pairs.append(ray_tpu.remote(num_returns=2)(_zip_task).remote(
                left[i].block_ref, rblock))
        # launch everything, then ONE batched metadata fetch
        metas = ray_tpu.get([m for _b, m in pairs])
        for i, ((b, _m), meta) in enumerate(zip(pairs, metas)):
            st.outqueue.append(RefBundle(b, meta, order=i))

    # -- streaming shuffle --------------------------------------------
    def _step_shuffle(self, op: AllToAllPhysicalOp, st: _OpState) -> bool:
        """Advance one pipelined random_shuffle op: scatter queued input
        bundles as map tasks (bounded window), launch reduce waves whose
        member shards have all arrived, finish when drained. Unlike the
        barrier all-to-alls, the first reduce launches after only
        ``fanin`` maps complete and at most ``window`` map shard sets
        ever exist at once."""
        ss = self.shuffle_states.get(id(op))
        if ss is None:
            ss = self.shuffle_states[id(op)] = _ShuffleState(op, self.ctx)
        progressed = False
        q = st.inqueues[0]

        # Scatter: dispatch eligible queued bundles. Eligibility is by
        # order — only maps within `window` of the oldest unlaunched
        # wave may start, so the shard buffer can't fill with late maps
        # while an early wave still needs a slot.
        if q:
            eligible_max = ss.reduce_wave * ss.fanin + ss.window
            for bundle in sorted(q, key=lambda b: b.order):
                if (len(ss.shards) + ss.maps_in_flight >= ss.window
                        or bundle.order >= eligible_max):
                    break
                q.remove(bundle)
                size = bundle.metadata.size_bytes or 0
                ss.bytes_map_in += size
                self._metric("counter", "ray_tpu_data_shuffle_bytes_total",
                             {"stage": "map"}, size)
                seed_j = None if op.seed is None else op.seed + bundle.order
                shards = ray_tpu.remote(num_returns=ss.n_out)(
                    _shuffle_map_task).remote(bundle.block_ref, ss.n_out,
                                              seed_j)
                shards = (shards,) if ss.n_out == 1 else tuple(shards)
                rec = _flight.RECORDER
                if rec is not None:
                    ss.flight_t0[("map", bundle.order)] = rec.clock()
                self.pending[shards[0]] = (
                    "shuffle_map", op, bundle.order, shards)
                ss.maps_in_flight += 1
                ss.maps_dispatched += 1
                st.in_flight += 1
                ss.note_in_flight()
                progressed = True

        # The total map count becomes known once upstream finished and
        # the inqueue fully drained into map tasks; that sizes the final
        # (possibly partial) wave.
        if st.inputs_done[0] and not q and ss.n_maps is None:
            ss.n_maps = ss.maps_dispatched
            progressed = True

        # Reduce waves: launch (wave, reducer) pairs in order while the
        # wave's member shards are all present. Pre-EOS only full-size
        # waves qualify (a short span might still gain members).
        while (ss.reduces_in_flight < self.ctx.max_tasks_in_flight_per_op
               and len(st.outqueue) < self.ctx.max_blocks_in_op_output_queue):
            lo, hi = ss.wave_span(ss.reduce_wave)
            if ss.n_maps is not None and lo >= ss.n_maps:
                break  # every wave launched
            if not all(j in ss.shards for j in range(lo, hi)):
                break
            i, w = ss.reduce_i, ss.reduce_wave
            seed = (None if op.seed is None
                    else op.seed + 7919 * (i + 1) + 104729 * w)
            b_ref, m_ref = ray_tpu.remote(num_returns=2)(
                _shuffle_reduce_task).remote(
                    seed, *[ss.shards[j][i] for j in range(lo, hi)])
            rec = _flight.RECORDER
            if rec is not None:
                ss.flight_t0[("reduce", w * ss.n_out + i)] = rec.clock()
            self.pending[m_ref] = (
                "shuffle_reduce", op, b_ref, w * ss.n_out + i)
            ss.reduces_in_flight += 1
            st.in_flight += 1
            ss.reduce_i += 1
            if ss.reduce_i >= ss.n_out:
                for j in range(lo, hi):
                    del ss.shards[j]  # wave fully launched; free slots
                ss.reduce_i = 0
                ss.reduce_wave += 1
            ss.note_in_flight()
            progressed = True

        if (not st.finished and st.inputs_done[0] and not q
                and ss.n_maps is not None and ss.maps_in_flight == 0
                and ss.reduces_in_flight == 0 and ss.all_waves_launched()):
            self._mark_finished(op)
            progressed = True
        return progressed

    # -- main loop ----------------------------------------------------
    def execute(self):
        """Generator of output RefBundles from the DAG's sink op."""
        try:
            yield from self._execute()
        finally:
            self._flush_metrics()

    def _execute(self):
        sink = self.dag
        sink_state = self.states[id(sink)]
        # Seed InputData ops.
        for op in self.topo:
            st = self.states[id(op)]
            if isinstance(op, InputDataOp):
                for i, b in enumerate(op.bundles):
                    b = RefBundle(b.block_ref, b.metadata, order=i)
                    if op is sink:
                        st.outqueue.append(b)
                    else:
                        self._forward(op, b)
                self._mark_finished(op)

        # In-order yield: hold back bundles until their predecessor
        # (by order index) has been emitted; flush sorted on finish.
        hold: Dict[int, RefBundle] = {}
        next_expected = 0

        def drain_sink():
            nonlocal next_expected
            while sink_state.outqueue:
                b = sink_state.outqueue.popleft()
                hold[b.order] = b
            while next_expected in hold:
                yield hold.pop(next_expected)
                next_expected += 1

        while True:
            yield from drain_sink()
            if sink_state.finished and not sink_state.outqueue \
                    and sink_state.in_flight == 0:
                for k in sorted(hold):
                    yield hold.pop(k)
                return

            progressed = self._step()
            if not progressed and not self.pending:
                # Nothing in flight and nothing dispatched: check finish.
                if sink_state.outqueue:
                    continue
                if sink_state.finished:
                    return
                # All upstream finished but sink not marked: finish ops
                # whose inputs are exhausted.
                stuck = True
                for op in self.topo:
                    st = self.states[id(op)]
                    if (not st.finished and st.all_inputs_done()
                            and st.in_flight == 0 and not st.has_input()):
                        self._mark_finished(op)
                        stuck = False
                if stuck:
                    raise RuntimeError(
                        "streaming executor deadlock: no progress possible")

    def _step(self) -> bool:
        progressed = False
        self._steps += 1
        if self._steps % _METRIC_FLUSH_STEPS == 0:
            self._flush_metrics()
        self.resource_manager.refresh()
        for st in self.states.values():  # reap idle autoscaled actors
            if st.actor_pool is not None:
                st.actor_pool.maybe_scale_down()
        # 1. Completions: block briefly for the first one, then sweep up
        # everything else already finished and fetch ALL their metadata
        # in one batched get (one round-trip per wave, not per bundle).
        if self.pending:
            refs = list(self.pending.keys())
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.02)
            if ready:
                more, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                       timeout=0)
                ready = more or ready
            done = [(r, self.pending.pop(r)) for r in ready]
            meta_refs = [r for r, ent in done if ent[0] != "shuffle_map"]
            metas = dict(zip(meta_refs, ray_tpu.get(meta_refs))) \
                if meta_refs else {}
            for m_ref, ent in done:
                kind, op = ent[0], ent[1]
                st = self.states[id(op)]
                if kind == "shuffle_map":
                    _tag, _op, order, shard_refs = ent
                    ss = self.shuffle_states[id(op)]
                    ss.shards[order] = shard_refs
                    ss.maps_in_flight -= 1
                    ss.maps_done += 1
                    st.in_flight -= 1
                    rec = _flight.RECORDER
                    if rec is not None:
                        t0 = ss.flight_t0.pop(("map", order), None)
                        if t0 is not None:
                            rec.record("shuffle", "map_wave", t0,
                                       rec.clock() - t0,
                                       {"order": order})
                    progressed = True
                    continue
                meta = metas[m_ref]
                if kind == "shuffle_reduce":
                    _tag, _op, b_ref, order = ent
                    ss = self.shuffle_states[id(op)]
                    ss.reduces_in_flight -= 1
                    ss.outputs_emitted += 1
                    ss.bytes_reduce_out += meta.size_bytes or 0
                    self._metric("counter", "ray_tpu_data_shuffle_bytes_total",
                                 {"stage": "reduce"}, meta.size_bytes or 0)
                    if ss.first_output_maps_done is None:
                        ss.first_output_maps_done = ss.maps_done
                    rec = _flight.RECORDER
                    if rec is not None:
                        t0 = ss.flight_t0.pop(("reduce", order), None)
                        if t0 is not None:
                            rec.record(
                                "shuffle", "reduce_wave", t0,
                                rec.clock() - t0,
                                {"order": order,
                                 "wave": order // ss.n_out,
                                 "bytes": meta.size_bytes or 0})
                    actor_idx = None
                else:
                    _tag, _op, b_ref, actor_idx, order = ent
                st.in_flight -= 1
                if actor_idx is not None and st.actor_pool is not None:
                    st.actor_pool.release(actor_idx)
                bundle = RefBundle(b_ref, meta, order=order)
                if op is self.dag:
                    st.outqueue.append(bundle)
                else:
                    self._forward(op, bundle)
                progressed = True

        # 2. Finish ops with exhausted inputs (and no in-flight work).
        for op in self.topo:
            st = self.states[id(op)]
            if st.finished:
                continue
            if isinstance(op, ReadPhysicalOp):
                if not st.pending_reads and st.in_flight == 0:
                    self._mark_finished(op)
                    progressed = True
            elif isinstance(op, AllToAllPhysicalOp):
                if st.all_inputs_done() and st.in_flight == 0 \
                        and not st.outqueue and not st.finished:
                    pass  # handled below (barrier needs the inqueue intact)
            elif st.all_inputs_done() and st.in_flight == 0:
                self._mark_finished(op)
                progressed = True

        # 3. Barrier ops whose inputs are complete.
        for op in self.topo:
            st = self.states[id(op)]
            if st.finished:
                continue
            if isinstance(op, AllToAllPhysicalOp) \
                    and op.kind == "random_shuffle":
                # Streamed, not a barrier — see _step_shuffle below.
                if self._step_shuffle(op, st):
                    progressed = True
            elif isinstance(op, AllToAllPhysicalOp) and st.inputs_done[0] \
                    and st.in_flight == 0:
                self._run_all_to_all(op, st)
                for b in list(st.outqueue) if op is not self.dag else []:
                    self._forward(op, b)
                if op is not self.dag:
                    st.outqueue.clear()
                self._mark_finished(op)
                progressed = True
            elif isinstance(op, JoinPhysicalOp) and all(st.inputs_done) \
                    and st.in_flight == 0:
                self._run_join(op, st)
                if op is not self.dag:
                    for b in list(st.outqueue):
                        self._forward(op, b)
                    st.outqueue.clear()
                self._mark_finished(op)
                progressed = True
            elif isinstance(op, ZipPhysicalOp) and all(st.inputs_done) \
                    and st.in_flight == 0:
                self._run_zip(op, st)
                if op is not self.dag:
                    for b in list(st.outqueue):
                        self._forward(op, b)
                    st.outqueue.clear()
                self._mark_finished(op)
                progressed = True

        # 4. Streaming passthrough ops (Limit, Union).
        for op in self.topo:
            st = self.states[id(op)]
            if st.finished:
                continue
            if isinstance(op, LimitPhysicalOp):
                while st.inqueues[0] and st.rows_emitted < op.limit:
                    # Consume strictly in order so the limit is
                    # deterministic under out-of-order completion.
                    want = st.emit_counter
                    match = next((b for b in st.inqueues[0]
                                  if b.order == want), None)
                    if match is None:
                        break
                    st.inqueues[0].remove(match)
                    st.emit_counter += 1
                    bundle = match
                    remaining = op.limit - st.rows_emitted
                    if bundle.metadata.num_rows > remaining:
                        b, _m = ray_tpu.remote(num_returns=2)(
                            _slice_task).remote(bundle.block_ref, 0, remaining)
                        # Derive the sliced metadata locally instead of a
                        # blocking get: row count is exact, bytes scale.
                        old = bundle.metadata
                        frac = remaining / max(old.num_rows, 1)
                        meta = BlockMetadata(
                            num_rows=remaining,
                            size_bytes=int((old.size_bytes or 0) * frac),
                            schema=old.schema,
                            input_files=old.input_files,
                            exec_stats=old.exec_stats)
                        bundle = RefBundle(b, meta, order=bundle.order)
                    st.rows_emitted += bundle.metadata.num_rows
                    if op is self.dag:
                        st.outqueue.append(bundle)
                    else:
                        self._forward(op, bundle)
                    progressed = True
                if st.rows_emitted >= op.limit or st.all_inputs_done():
                    self._mark_finished(op)
                    progressed = True
            elif isinstance(op, UnionPhysicalOp):
                for q in st.inqueues:
                    while q:
                        bundle = q.popleft()
                        bundle = RefBundle(bundle.block_ref, bundle.metadata,
                                           order=st.emit_counter)
                        st.emit_counter += 1
                        if op is self.dag:
                            st.outqueue.append(bundle)
                        else:
                            self._forward(op, bundle)
                        progressed = True
                if st.all_inputs_done():
                    self._mark_finished(op)
                    progressed = True

        # 5. Dispatch new tasks, downstream ops first (drain memory).
        for op in reversed(self.topo):
            st = self.states[id(op)]
            if st.finished or isinstance(
                    op, (AllToAllPhysicalOp, ZipPhysicalOp, JoinPhysicalOp,
                         LimitPhysicalOp, UnionPhysicalOp, InputDataOp)):
                continue
            while st.has_input() and st.under_limits():
                if (isinstance(op, ReadPhysicalOp)
                        and not self.resource_manager.allow_source_dispatch()
                        and self._work_elsewhere(op)):
                    # memory backpressure: sources pause while queued
                    # bytes exceed the budget — unless nothing else can
                    # progress, which would deadlock the pipeline
                    break
                self._dispatch(op, st)
                progressed = True
        return progressed

    def _work_elsewhere(self, source: PhysicalOp) -> bool:
        """True if something else can make progress THIS step — i.e.
        pausing this source cannot deadlock the stream. Barrier ops
        (sort/join/zip/...) buffering input do NOT count: they can't run
        until their sources finish, so treating their backlog as
        progress would pause the source forever and trip the executor's
        deadlock check."""
        if self.pending:
            return True
        for other in self.topo:
            if other is source or isinstance(other, ReadPhysicalOp):
                continue
            st = self.states[id(other)]
            if st.finished:
                continue
            if isinstance(other, (AllToAllPhysicalOp, ZipPhysicalOp,
                                  JoinPhysicalOp)):
                if all(st.inputs_done) and st.has_input():
                    return True  # barrier will actually fire this step
            elif st.has_input():
                return True
        return False
