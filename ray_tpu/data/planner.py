"""Planner: logical DAG -> physical DAG, with map-operator fusion.

reference: python/ray/data/_internal/planner/planner.py plus the fusion
rule in _internal/logical/rules/operator_fusion.py — adjacent map-family
operators collapse into one MapPhysicalOp applying a fused transform
chain in a single task.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.data import logical as L
from ray_tpu.data.context import DataContext
from ray_tpu.data.execution import (
    AllToAllPhysicalOp,
    InputDataOp,
    LimitPhysicalOp,
    MapPhysicalOp,
    PhysicalOp,
    ReadPhysicalOp,
    RefBundle,
    UnionPhysicalOp,
    WritePhysicalOp,
    JoinPhysicalOp,
    ZipPhysicalOp,
)
from ray_tpu.data.transforms import MapTransform


def _to_transform(op: L.AbstractMap, ctx: DataContext) -> MapTransform:
    return MapTransform(
        kind=op.kind, fn=op.fn, fn_args=op.fn_args, fn_kwargs=op.fn_kwargs,
        batch_size=op.batch_size,
        batch_format=op.batch_format or ctx.default_batch_format)


def _fusable(a: L.AbstractMap, b: L.AbstractMap) -> bool:
    # Actor-compute ops only fuse with identical compute/concurrency;
    # differing resource requests block fusion.
    return (a.compute == b.compute and a.concurrency == b.concurrency
            and a.resources == b.resources)


class Planner:
    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()

    def plan(self, plan: L.LogicalPlan) -> PhysicalOp:
        return self._lower(plan.dag, {})

    def _lower(self, op: L.LogicalOp, memo: Dict[int, PhysicalOp]) -> PhysicalOp:
        if id(op) in memo:
            return memo[id(op)]
        result = self._lower_one(op, memo)
        memo[id(op)] = result
        return result

    def _lower_one(self, op: L.LogicalOp, memo) -> PhysicalOp:
        if isinstance(op, L.Read):
            return ReadPhysicalOp(op.read_tasks, name=op.name)
        if isinstance(op, L.InputData):
            bundles = [RefBundle(r, m)
                       for r, m in zip(op.block_refs, op.metadata)]
            return InputDataOp(bundles)
        if isinstance(op, L.AbstractMap):
            # Collect the maximal fusable chain ending at `op`.
            chain: List[L.AbstractMap] = [op]
            cur = op
            while (self.ctx.enable_operator_fusion
                   and isinstance(cur.inputs[0], L.AbstractMap)
                   and _fusable(cur.inputs[0], cur)
                   and id(cur.inputs[0]) not in memo):
                cur = cur.inputs[0]
                chain.append(cur)
            chain.reverse()
            upstream = self._lower(chain[0].inputs[0], memo)
            transforms = [_to_transform(c, self.ctx) for c in chain]
            name = "->".join(c.name for c in chain)
            return MapPhysicalOp(
                transforms, upstream, compute=op.compute,
                concurrency=op.concurrency, resources=op.resources, name=name)
        if isinstance(op, L.AbstractAllToAll):
            upstream = self._lower(op.inputs[0], memo)
            return AllToAllPhysicalOp(
                op.kind, upstream, num_outputs=op.num_outputs, key=op.key,
                descending=op.descending, seed=op.seed, aggs=op.aggs,
                name=op.name)
        if isinstance(op, L.Limit):
            return LimitPhysicalOp(self._lower(op.inputs[0], memo), op.limit)
        if isinstance(op, L.Union):
            return UnionPhysicalOp([self._lower(i, memo) for i in op.inputs])
        if isinstance(op, L.Join):
            return JoinPhysicalOp(self._lower(op.inputs[0], memo),
                                  self._lower(op.inputs[1], memo),
                                  on=op.on, how=op.how,
                                  num_partitions=op.num_partitions)
        if isinstance(op, L.Zip):
            return ZipPhysicalOp(self._lower(op.inputs[0], memo),
                                 self._lower(op.inputs[1], memo))
        if isinstance(op, L.Write):
            return WritePhysicalOp(op.write_fn,
                                   self._lower(op.inputs[0], memo),
                                   name=op.name)
        raise TypeError(f"cannot lower logical op {op!r}")
