"""Device object store: ObjectRefs whose payload stays on-device (HBM).

Reference: python/ray/experimental/gpu_object_manager/
(gpu_object_manager.py:84, gpu_object_store.py) — "RDT" refs whose
tensor payload never moves through plasma; only metadata does, and
transfer happens out-of-band between the owning and consuming actors.

TPU-native stance (SURVEY.md §2.3 X6): there is no CUDA-IPC analog for
HBM across host processes, and ICI collectives only exist inside jitted
programs. So a device ref's payload lives in the *owner process's* JAX
client; the object plane carries a small metadata record. Consumers on
the same process get the live `jax.Array` (zero transfer); consumers
elsewhere trigger one owner-side device→host copy, a shared-memory hop
(zero-copy numpy both ways), and a `device_put` — the staging pattern
the object plane is the right transport for on a TPU host. Same-mesh
SPMD math should never use this path: keep arrays inside one jitted
program and let XLA move bytes over ICI.

Usage:
    ref = device_objects.put(array)          # inside any actor/driver
    arr = device_objects.get(ref)            # anywhere; device_put as needed
    device_objects.free(ref)
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.object_ref import ObjectRef

# per-process payload registry: uid -> jax.Array
_registry: Dict[bytes, Any] = {}


@dataclass
class DeviceObjectMeta:
    """What actually travels through the object plane."""

    uid: bytes
    shape: Tuple[int, ...]
    dtype: str
    owner: Optional[ActorHandle]  # None => owned by the driver
    # driver-owned objects inline a host copy (the driver serves no RPCs)
    inline_host: Optional[np.ndarray] = field(default=None, repr=False)


def _own_handle() -> Optional[ActorHandle]:
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    actor_id = getattr(rt, "actor_id", None)
    if actor_id is None:
        return None
    return ActorHandle(actor_id, "<device-object-owner>", [])


def put(array) -> ObjectRef:
    """Register a device array; returns a ref to its metadata record."""
    import ray_tpu

    from ray_tpu.core import runtime as runtime_mod

    uid = os.urandom(16)
    owner = _own_handle()
    inline = None
    if owner is None:
        # Driver or plain (non-actor) task: consumers can't call back in,
        # so ship a host copy with the metadata. Only the driver keeps a
        # registry entry (its process persists); a transient task worker
        # must not pin HBM it can never be asked to free.
        inline = np.asarray(array)
        if getattr(runtime_mod.get_runtime(), "is_driver", False):
            _registry[uid] = array
    else:
        _registry[uid] = array
    meta = DeviceObjectMeta(
        uid=uid, shape=tuple(array.shape), dtype=str(array.dtype),
        owner=owner, inline_host=inline)
    return ray_tpu.put(meta)


def _export(instance, uid: bytes) -> np.ndarray:
    """Owner-side fetch handler (runs via __ray_call__)."""
    array = _registry.get(uid)
    if array is None:
        raise KeyError(f"device object {uid.hex()} was freed or never "
                       "existed on this owner")
    return np.asarray(array)  # device -> host


def _drop(instance, uid: bytes) -> bool:
    return _registry.pop(uid, None) is not None


def _resolve_meta(ref, timeout) -> DeviceObjectMeta:
    # task args holding the ref arrive pre-resolved as the meta record
    if isinstance(ref, DeviceObjectMeta):
        return ref
    import ray_tpu
    meta = ray_tpu.get(ref, timeout=timeout)
    if not isinstance(meta, DeviceObjectMeta):
        raise TypeError(f"{ref} is not a device object ref")
    return meta


def get(ref, *, device=None, sharding=None,
        timeout: Optional[float] = 60.0):
    """Resolve a device ref (or its meta record) to a jax.Array here.

    Same-process: returns the live array. Remote: one owner device→host
    copy + shm hop, then `device_put` onto `device`/`sharding` (default:
    JAX's default device).
    """
    import jax
    import ray_tpu

    meta = _resolve_meta(ref, timeout)
    local = _registry.get(meta.uid)
    if local is not None:
        if device is None and sharding is None:
            return local
        host = np.asarray(local)
    elif meta.owner is None:
        host = meta.inline_host
    else:
        fetch = meta.owner.__ray_call__.remote(_export, meta.uid)
        host = ray_tpu.get(fetch, timeout=timeout)
    placement = sharding or device
    if placement is None:
        return jax.numpy.asarray(host)
    return jax.device_put(host, placement)


def free(ref, timeout: Optional[float] = 30.0) -> None:
    """Drop the device payload (metadata record stays until GC'd)."""
    import ray_tpu

    meta = _resolve_meta(ref, timeout)
    if _registry.pop(meta.uid, None) is not None:
        return
    if meta.owner is not None:
        ray_tpu.get(meta.owner.__ray_call__.remote(_drop, meta.uid),
                    timeout=timeout)
