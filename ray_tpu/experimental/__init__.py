"""Experimental subsystems (reference: python/ray/experimental/)."""
