"""Weight-only int8 matmul Pallas kernel.

Decode-time matmuls are HBM-bandwidth-bound: the whole weight matrix
streams from HBM for a handful of batch rows. Storing weights as int8
with per-output-channel scales halves that traffic — but ONLY if the
dequantization happens in-register after the tile load. XLA does not
fuse `w8.astype(bf16) * scale` into the dot's operand read (measured:
it materializes the bf16 weights, erasing the win), so the dequant
lives inside this kernel: each [bk, bn] int8 tile is converted in
VMEM right before the MXU dot.

No reference analog (the reference delegates quantized serving to
vLLM's CUDA kernels); TPU-native design per the Pallas guide's tiled
matmul pattern.

Measured (round 4, axon-virtualized v5 lite): 8% end-to-end FFN-loop
win over the XLA bf16 path at batch 32 — this chip's effective HBM
bandwidth is ~10x below real-v5e spec (72-143 GB/s observed), so
neither path is weight-bandwidth-bound and the halved traffic cannot
pay out. On full-bandwidth hardware, weight-bound decode is where
this kernel earns its 2x; rel. quantization error ~0.8%.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["scale_from_amax", "quantize_int8", "int8_matmul"]

# Run the kernel in interpreter mode (CPU testing); toggled by tests.
_INTERPRET = False


def scale_from_amax(amax, qmax: float = 127.0):
    """Symmetric quantization scale from a per-group |max|: the one
    piece of scale math shared by this kernel's weight quantization and
    the quantized collectives (parallel/collective.quantized_psum).
    ``qmax``: 127 for int8, 448 for fp8-e4m3."""
    return jnp.maximum(jnp.asarray(amax, jnp.float32) / qmax, 1e-8)


def quantize_int8(w, axis: int = 0):
    """Symmetric per-output-channel int8 quantization.

    w: [K, N] float -> (w8 [K, N] int8, scale [N] f32) with
    w ~= w8 * scale.
    """
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = scale_from_amax(amax)
    w8 = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return w8, scale.reshape(-1)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # dequant in-register: int8 tile -> bf16 just before the MXU dot
    w = w_ref[...].astype(jnp.bfloat16)
    acc_ref[...] += jnp.dot(x_ref[...], w,
                            preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _done():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(jnp.bfloat16)


def int8_matmul(x, w8, scale, *, block_n: int = 512,
                block_k: int = 1024):
    """x [B, K] bf16 @ (w8 [K, N] int8 * scale [N]) -> [B, N] bf16.

    B is padded to the 16-row sublane tile; K and N must divide by the
    block sizes (model dims here are multiples of 1024).
    """
    # interpret is a STATIC jit arg, not a baked-in global read — a
    # module-jitted read of _INTERPRET would cache whichever mode ran
    # first per shape and silently reuse it after the toggle flips.
    return _int8_matmul_impl(x, w8, scale, block_n=block_n,
                             block_k=block_k, interpret=_INTERPRET)


@functools.partial(jax.jit, static_argnames=("block_n", "block_k",
                                             "interpret"))
def _int8_matmul_impl(x, w8, scale, *, block_n, block_k, interpret):
    b, k_dim = x.shape
    _, n_dim = w8.shape
    block_k = min(block_k, k_dim)
    block_n = min(block_n, n_dim)
    if k_dim % block_k or n_dim % block_n:
        raise ValueError(f"dims ({k_dim},{n_dim}) must divide blocks "
                         f"({block_k},{block_n})")
    if scale.shape[0] != n_dim:
        raise ValueError(f"scale length {scale.shape[0]} != N {n_dim} "
                         "(out-of-range block reads clamp SILENTLY on "
                         "TPU — quantize per output channel, axis=0)")
    b_pad = max(16, -(-b // 16) * 16)
    if b_pad != b:
        x = jnp.pad(x, ((0, b_pad - b), (0, 0)))
    n_k = k_dim // block_k
    grid = (n_dim // block_n, n_k)
    out = pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_pad, block_k), lambda j, k: (0, k)),
            pl.BlockSpec((block_k, block_n), lambda j, k: (k, j)),
            # scale rides as [1, N]: 2-D keeps Mosaic/XLA layouts agreed
            pl.BlockSpec((1, block_n), lambda j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((b_pad, block_n), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b_pad, n_dim), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((b_pad, block_n), jnp.float32)],
        interpret=interpret,
    )(x, w8, scale.astype(jnp.float32).reshape(1, -1))
    return out[:b]
