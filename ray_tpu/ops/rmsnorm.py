"""Fused RMSNorm.

On TPU the win is fusing the reduction + rescale into one VMEM pass so
the activation is read from HBM once. XLA usually fuses this pattern by
itself; the Pallas kernel exists to guarantee it on the hot path and to
serve as the template for further fused epilogues.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_norm_reference(x, weight, eps: float):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_pallas(x, weight, eps: float):
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(512, rows)
    grid = (rows // block_rows,)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
    )(x2, weight)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm over the last axis: x * rsqrt(mean(x^2)+eps) * weight."""
    d = x.shape[-1]
    rows = x.size // d
    use_kernel = (
        jax.default_backend() in ("tpu", "axon")
        and d % 128 == 0
        and rows % min(512, rows) == 0
        and rows >= 8
    )
    if use_kernel:
        return _rms_pallas(x, weight, eps)
    return _rms_norm_reference(x, weight, eps)


def _fwd(x, weight, eps):
    return rms_norm(x, weight, eps), (x, weight)


def _bwd(eps, res, g):
    x, weight = res
    _, vjp = jax.vjp(lambda x_, w_: _rms_norm_reference(x_, w_, eps),
                     x, weight)
    return vjp(g)


rms_norm.defvjp(_fwd, _bwd)
