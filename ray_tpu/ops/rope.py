"""Rotary position embeddings (RoPE).

Pure elementwise math — XLA fuses it into the surrounding projections,
so no Pallas kernel is needed; a hand kernel would only pin a layout
the compiler might beat."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float = 10000.0):
    """Precompute cos/sin tables: [max_seq_len, head_dim//2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin, positions=None):
    """Rotate pairs of channels. x: [B, S, H, D]; cos/sin: [S_max, D//2];
    positions: [B, S] optional absolute positions (default arange)."""
    b, s, h, d = x.shape
    if positions is None:
        cos_sel = cos[:s][None, :, None, :]       # [1, S, 1, D/2]
        sin_sel = sin[:s][None, :, None, :]
    else:
        cos_sel = cos[positions][:, :, None, :]   # [B, S, 1, D/2]
        sin_sel = sin[positions][:, :, None, :]
    x1 = x[..., : d // 2]
    x2 = x[..., d // 2:]
    out1 = x1 * cos_sel - x2 * sin_sel
    out2 = x2 * cos_sel + x1 * sin_sel
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
