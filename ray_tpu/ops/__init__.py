"""TPU compute kernels (Pallas) with jnp references.

The hot ops of the transformer stack: fused attention (flash),
fused RMSNorm, rotary embeddings, weight-only int8 matmul. Each op
exposes a reference implementation used for tests/CPU and a Pallas
TPU kernel selected automatically on TPU backends."""

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.quant_matmul import int8_matmul, quantize_int8
from ray_tpu.ops.rmsnorm import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
