"""Fused causal attention.

A Pallas TPU kernel that computes attention per (batch, head, q-block)
entirely in VMEM — the [S, S] score matrix never materializes in HBM,
which is the memory win that matters on TPU (HBM bandwidth is the
bottleneck; VMEM blocks feed the MXU directly). Falls back to a jnp
reference off-TPU and for shapes the kernel doesn't cover.

Backward runs the reference VJP on recomputed activations (flash-style
fused backward kernel is future work; `jax.checkpoint` around the call
already keeps residuals small).

Layout: [batch, seq, heads, head_dim] (GQA supported by repeating K/V
heads upstream in the model).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Max K/V bytes held in VMEM per (batch, head) program before falling
# back (v5 VMEM ~16 MB/core; leave room for q/out/scores).
_VMEM_KV_BUDGET = 8 * 1024 * 1024
_BLOCK_Q = 256


def _attention_reference(q, k, v, causal: bool):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), sk - sq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool,
                  block_q: int, seq_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)           # [block_q, d]
    k = k_ref[0, 0, :, :].astype(jnp.float32)           # [seq_k, d]
    v = v_ref[0, 0, :, :].astype(jnp.float32)
    d = q.shape[-1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (1.0 / (d ** 0.5))
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, seq_k), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, seq_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / l
    o_ref[0, 0, :, :] = o.astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(_BLOCK_Q, sq)
    grid = (b, h, sq // block_q)
    kernel = functools.partial(_flash_kernel, causal=causal,
                               block_q=block_q, seq_k=sk)
    # Kernel layout is [B, H, S, D] so the tiled (second-to-last, last)
    # dims are (seq, head_dim) — the MXU-friendly orientation. XLA fuses
    # the transposes into the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def _kernel_supported(q, k) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    # sq must tile exactly by the q block actually used (min(_BLOCK_Q,
    # sq)) — the grid floor-divides, so a 128-aligned-but-not-block-
    # aligned tail would be left unwritten.
    if d % 128 or sq % 128 or sk % 128 or sq % min(_BLOCK_Q, sq):
        return False
    kv_bytes = 2 * sk * d * 4
    return kv_bytes <= _VMEM_KV_BUDGET


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = True):
    """Fused causal attention: [B, S, H, D] x3 -> [B, S, H, D].

    K/V head count must equal Q head count (expand GQA groups first)."""
    if _kernel_supported(q, k):
        return _flash_forward(q, k, v, causal)
    return _attention_reference(q, k, v, causal)


def _fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal), (q, k, v)


def _bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _attention_reference(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
