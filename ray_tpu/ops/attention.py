"""Fused causal flash attention — streaming Pallas TPU kernels.

Forward: online-softmax accumulation over K/V tiles (FlashAttention
algorithm) with a (batch, head, q-block, k-block) grid — VMEM stays
bounded at any sequence length, the [S, S] score matrix never touches
HBM, and causally-masked K blocks are skipped (their compute is
predicated off and their DMAs elided by clamping the block index map to
the last valid block, so Mosaic's pipeline sees a repeated index and
re-uses the buffer).

Backward: fused dq and dk/dv kernels using the saved logsumexp and the
precomputed delta = rowsum(dO * O) — no score-matrix materialization in
the backward either, which is where the naive VJP loses (a
[B, H, S, S] f32 tensor per layer is HBM-bandwidth death at seq 2048+).

All matmuls run with bf16 inputs and f32 accumulation
(preferred_element_type) — the MXU's native mode; softmax statistics
stay f32.

Reference analog: the reference has no in-tree attention kernels (it
delegates to vLLM/torch, SURVEY.md §5.7); this is the TPU-native
equivalent the blueprint commits to.

Layout: [batch, seq, heads, head_dim] (GQA supported by repeating K/V
heads upstream in the model).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.parallel import _compat

NEG_INF = -1e30

# Default tile sizes; shrunk to fit when seq is smaller. 128-multiples
# keep every matmul MXU-aligned. 256x512 measured ~4x faster than
# 512x512 on v5e (the [bq, bk] f32 score tile plus double-buffered
# operands stays within VMEM without spilling).
_BLOCK_Q = 256
_BLOCK_K = 512
# Run kernels in interpreter mode (CPU testing); toggled by tests.
_INTERPRET = False


def _block_size(pref: int, dim: int) -> Optional[int]:
    """Largest 128-multiple block <= pref that tiles `dim` exactly."""
    for cand in (pref, 256, 128):
        if cand <= dim and dim % cand == 0:
            return cand
    return None


def _attention_reference(q, k, v, causal: bool):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), sk - sq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)



# --- shared causal-geometry helpers (keep forward/backward in sync) ----

def _causal_live(qi, ki, block_q: int, block_k: int, offset: int):
    """Whether the (qi, ki) tile touches the causal lower triangle."""
    return (qi + 1) * block_q - 1 + offset >= ki * block_k


def _causal_mask(s, qi, ki, block_q: int, block_k: int, offset: int):
    """NEG_INF-mask score tile entries above the causal diagonal."""
    q_pos = qi * block_q + offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _clamped_kv_index(causal: bool, block_q: int, block_k: int,
                      offset: int, nk: int):
    """KV block index map: past-diagonal fetches clamp to the last live
    block, so Mosaic sees a repeated index and elides the DMA."""
    def index(bi, hi, qi, ki):
        if causal:
            last = jnp.minimum(
                ((qi + 1) * block_q - 1 + offset) // block_k, nk - 1)
            ki = jnp.minimum(ki, last)
        return (bi, hi, ki, 0)
    return index


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal: bool, sm_scale: float, block_q: int,
                block_k: int, offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = (_causal_live(qi, ki, block_q, block_k, offset) if causal
           else ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                                   # [bq, d] bf16
        k = k_ref[0, 0]                                   # [bk, d] bf16
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        m_prev = m_scr[...]                               # [bq, 128]
        m_cur = jnp.max(s, axis=-1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)                # broadcast
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])     # [bq, 1]
        p = jnp.exp(s - m_new[:, :1])                     # [bq, bk] f32
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[...] * alpha
        acc += jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new[:, :1], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[...] = acc

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked row guard
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(l)          # [bq, 1]


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int):
    """q,k,v: [B, H, S, D] -> (o [B, H, Sq, D], lse [B, H, Sq, 1] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    offset = sk - sq
    nq, nk = sq // block_q, sk // block_k
    grid = (b, h, nq, nk)

    kv_index = _clamped_kv_index(causal, block_q, block_k, offset, nk)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=d ** -0.5,
        block_q=block_q, block_k=block_k, offset=offset)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # trailing dim of 1 satisfies the (8, 128) tile rule via
            # the block-equals-array-dim escape hatch, without the 128x
            # lane padding the official kernel pays for its lse output
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),     # output accum
        ],
        compiler_params=_compat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal: bool, sm_scale: float, block_q: int,
               block_k: int, offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (_causal_live(qi, ki, block_q, block_k, offset) if causal
           else ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse_ref[0, 0])                     # [bq, bk]
        do = do_ref[0, 0]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                sm_scale: float, block_q: int, block_k: int, offset: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (_causal_live(qi, ki, block_q, block_k, offset) if causal
           else qi >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                                    # [bq, d]
        k = k_ref[0, 0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse_ref[0, 0])                     # [bq, bk]
        do = do_ref[0, 0]                                  # [bq, d]
        # dv += p^T @ do
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        # dk += ds^T @ q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, causal: bool, block_q: int,
                    block_k: int):
    """All tensors [B, H, S, D] (lse/delta [B, H, S]); returns dq/dk/dv."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    offset = sk - sq
    nq, nk = sq // block_q, sk // block_k
    sm_scale = d ** -0.5
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [B,H,Sq,1]

    q_idx = lambda bi, hi, qi, ki: (bi, hi, qi, 0)

    kv_idx = _clamped_kv_index(causal, block_q, block_k, offset, nk)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_idx),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
            pl.BlockSpec((1, 1, block_k, d), kv_idx),
            pl.BlockSpec((1, 1, block_q, d), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_idx),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(q, k, v, do, lse, delta)

    # dk/dv: iterate q blocks innermost for each k block. For causal,
    # early (fully-masked) q blocks clamp forward to the first live one.
    def q_idx_b(bi, hi, ki, qi):
        if causal:
            first = jnp.maximum((ki * block_k - offset) // block_q, 0)
            qi = jnp.maximum(qi, first)
        return (bi, hi, qi, 0)

    kv_idx_b = lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_idx_b),
            pl.BlockSpec((1, 1, block_k, d), kv_idx_b),
            pl.BlockSpec((1, 1, block_k, d), kv_idx_b),
            pl.BlockSpec((1, 1, block_q, d), q_idx_b),
            pl.BlockSpec((1, 1, block_q, 1), q_idx_b),
            pl.BlockSpec((1, 1, block_q, 1), q_idx_b),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), kv_idx_b),
            pl.BlockSpec((1, 1, block_k, d), kv_idx_b),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------

def _kernel_plan(q, k):
    """(block_q, block_k) if the kernels cover these shapes, else None."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if not (_INTERPRET or jax.default_backend() in ("tpu", "axon")):
        return None
    if d % 128 or sq % 128 or sk % 128:
        return None
    bq = _block_size(_BLOCK_Q, sq)
    bk = _block_size(_BLOCK_K, sk)
    if bq is None or bk is None:
        return None
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = True):
    """Fused causal attention: [B, S, H, D] x3 -> [B, S, H, D].

    K/V head count must equal Q head count (expand GQA groups first)."""
    plan = _kernel_plan(q, k)
    if plan is None:
        return _attention_reference(q, k, v, causal)
    # Kernel layout is [B, H, S, D] so the tiled (second-to-last, last)
    # dims are (seq, head_dim) — the MXU-friendly orientation. XLA fuses
    # the transposes into the surrounding projections.
    out, _ = _flash_forward(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal, *plan)
    return out.transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal):
    plan = _kernel_plan(q, k)
    if plan is None:
        return flash_attention(q, k, v, causal), (q, k, v, None, None)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, lse = _flash_forward(qt, kt, vt, causal, *plan)
    return out.transpose(0, 2, 1, 3), (q, k, v, out, lse)


def _bwd(causal, res, g):
    q, k, v, out, lse = res
    plan = _kernel_plan(q, k)
    if plan is None or out is None:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _attention_reference(q_, k_, v_, causal),
            q, k, v)
        return vjp(g)
    # `out` was saved in kernel layout [B, H, S, D] by _fwd.
    dq, dk, dv = _flash_backward(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), out, lse,
        g.transpose(0, 2, 1, 3), causal, *plan)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


flash_attention.defvjp(_fwd, _bwd)
