"""Serve configuration objects.

Capability parity with the reference's serve config (reference:
python/ray/serve/config.py AutoscalingConfig/HTTPOptions;
serve/_private/config.py DeploymentConfig/ReplicaConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """reference: python/ray/serve/config.py AutoscalingConfig +
    serve/autoscaling_policy.py target-ongoing-requests policy."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    # smoothed over this window of replica metric reports
    look_back_period_s: float = 2.0
    # "ongoing" (target-ongoing-requests, the reference default) or
    # "slo" (scale on router-reported queue depth + windowed p99
    # latency; see ray_tpu/autoscaler/policy.py)
    policy: str = "ongoing"
    # -- slo policy knobs --
    # sustained queue depth above this target is an SLO breach
    target_queue_depth: float = 4.0
    # sustained windowed p99 above this is a breach; <= 0 disables the
    # latency term (queue depth alone drives scaling)
    p99_latency_slo_s: float = 0.0
    # router stats older than this are ignored (idle routers stop
    # reporting; stale breach data must not pin the fleet scaled-up)
    slo_stats_staleness_s: float = 3.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 1.0
    graceful_shutdown_timeout_s: float = 5.0
    # routing policy: "pow2" (default) or "prefix_aware" (LLM
    # prompt-prefix cache affinity; reference:
    # llm/_internal/serve/routing_policies/prefix_aware/)
    request_router: str = "pow2"
    # -- admission control (ray_tpu/serve/admission.py) --
    # requests allowed to wait beyond replica capacity
    # (live_replicas * max_ongoing_requests) before new arrivals shed
    # with 503/BackpressureError; < 0 disables the cap (legacy
    # unbounded-queue behavior). 0 sheds the moment every replica slot
    # is full; 1 lets exactly one request wait.
    max_queued_requests: int = -1
    # shed when the EWMA of observed queue wait exceeds this many
    # seconds, even under the hard cap; <= 0 disables
    shed_queue_wait_s: float = 0.0


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
