"""Serve configuration objects.

Capability parity with the reference's serve config (reference:
python/ray/serve/config.py AutoscalingConfig/HTTPOptions;
serve/_private/config.py DeploymentConfig/ReplicaConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """reference: python/ray/serve/config.py AutoscalingConfig +
    serve/autoscaling_policy.py target-ongoing-requests policy."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    # smoothed over this window of replica metric reports
    look_back_period_s: float = 2.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 1.0
    graceful_shutdown_timeout_s: float = 5.0
    # routing policy: "pow2" (default) or "prefix_aware" (LLM
    # prompt-prefix cache affinity; reference:
    # llm/_internal/serve/routing_policies/prefix_aware/)
    request_router: str = "pow2"


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
