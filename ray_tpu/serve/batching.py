"""@serve.batch — dynamic request batching inside a replica.

Capability parity with the reference's batching (reference:
python/ray/serve/batching.py @serve.batch — requests accumulate up to
max_batch_size or batch_wait_timeout_s, the wrapped function runs once
on the list, results fan back out). Replicas execute requests on a
thread pool (actor max_concurrency), so the queue is thread-based
rather than asyncio-based; on TPU replicas this is what turns N
concurrent HTTP requests into one batched forward pass on the MXU.
"""

from __future__ import annotations

import functools
import queue
import threading

from ray_tpu.devtools import locktrace
from typing import Any, Callable, List, Optional

from ray_tpu.util.metrics import Histogram

# How full batches actually run (reference: serve batching metrics).
# On TPU replicas this is the realized MXU batch width — the first
# thing to check when throughput is below the roofline.
BATCH_SIZE = Histogram(
    "ray_tpu_serve_batch_size",
    "Realized @serve.batch batch sizes", tag_keys=("fn",),
    boundaries=[1, 2, 4, 8, 16, 32, 64, 128])
BATCH_WAIT = Histogram(
    "ray_tpu_serve_batch_wait_seconds",
    "Time one batch spent accumulating before execution",
    tag_keys=("fn",),
    boundaries=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0])


class _Pending:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float,
                 name: str = "batch"):
        self.fn = fn
        self.name = name
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.queue: "queue.Queue[_Pending]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = locktrace.traced_lock("serve.batching.queue")

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def _loop(self) -> None:
        import time
        while True:
            batch = [self.queue.get()]
            t0 = time.perf_counter()
            # Give the batch a window to fill (the MXU wants width).
            while len(batch) < self.max_batch_size:
                try:
                    batch.append(self.queue.get(timeout=self.timeout_s))
                except queue.Empty:
                    break
            BATCH_SIZE.observe(float(len(batch)), tags={"fn": self.name})
            BATCH_WAIT.observe(time.perf_counter() - t0,
                               tags={"fn": self.name})
            try:
                results = self.fn([p.item for p in batch])
                if results is None or len(results) != len(batch):
                    raise ValueError(
                        "@serve.batch function must return one result per "
                        f"input (got {results!r} for {len(batch)} inputs)")
                for p, r in zip(batch, results):
                    p.result = r
            except BaseException as e:  # propagate to every waiter
                for p in batch:
                    p.error = e
            for p in batch:
                p.event.set()

    def submit(self, item: Any) -> Any:
        self._ensure_thread()
        pending = _Pending(item)
        self.queue.put(pending)
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result


# Batcher state lives OUTSIDE the wrapper closure (keyed by the wrapper
# function object) so decorated classes stay picklable: a closure-held
# Lock/_Batcher would break cloudpickle when the deployment ships to a
# replica. The wrapper reaches this state through an in-body import —
# a direct global reference would get pickled by value along with the
# wrapper (whose __module__ is the user's, via functools.wraps).
_state_lock = locktrace.traced_lock("serve.batching.state")
_batchers: dict = {}  # (wrapper key, owner key) -> _Batcher


def _submit(key, call, item, max_batch_size, batch_wait_timeout_s,
            name="batch"):
    with _state_lock:
        b = _batchers.get(key)
        if b is None:
            b = _Batcher(call, max_batch_size, batch_wait_timeout_s,
                         name=name)
            _batchers[key] = b
    return b.submit(item)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``fn(self, items: list) -> list`` is called with up to
    max_batch_size accumulated single-call payloads."""

    def make(fn):
        @functools.wraps(fn)
        def wrapper(*args):
            from ray_tpu.serve import batching as _b
            if len(args) == 2:  # bound method: (self, item)
                owner, item = args
                key = (id(wrapper), id(owner))
                call = lambda items: fn(owner, items)  # noqa: E731
            elif len(args) == 1:  # plain function: (item,)
                (item,) = args
                key = (id(wrapper), None)
                call = fn
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request "
                    "argument")
            return _b._submit(key, call, item, max_batch_size,
                              batch_wait_timeout_s,
                              name=getattr(fn, "__qualname__", "batch"))

        return wrapper

    if _fn is not None:
        return make(_fn)
    return make
