"""DeploymentHandle: call deployments from Python (model composition).

Capability parity with the reference's handle API (reference:
python/ray/serve/handle.py DeploymentHandle/DeploymentResponse —
``handle.remote()`` returns a response future; ``.options(method_name=)``
targets methods; handles serialize so replicas can call downstream
deployments).
"""

from __future__ import annotations

import threading

from ray_tpu.devtools import locktrace
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.serve.admission import BackpressureError, Shed
from ray_tpu.serve.replica import Rejected
from ray_tpu.serve.router import Router

_routers: Dict[str, Router] = {}
# deployments whose routing policy could not be fetched yet (their
# provisional pow-2 router is upgraded once the controller answers)
_routers_unresolved: set = set()
_routers_lock = locktrace.traced_lock("serve.handle.routers")


def _get_router(deployment_name: str, controller) -> Router:
    with _routers_lock:
        router = _routers.get(deployment_name)
        needs_policy = (router is None
                        or deployment_name in _routers_unresolved)
    if not needs_policy:
        return router
    # Policy fetch happens OUTSIDE the lock (it is a controller RPC; a
    # slow controller must not stall handle calls for every cached
    # deployment). A failed fetch falls back to pow-2 but stays marked
    # unresolved, so the next call retries instead of silently pinning
    # the wrong policy forever.
    import ray_tpu
    policy = None
    try:
        policy = ray_tpu.get(
            controller.get_router_policy.remote(deployment_name),
            timeout=10)
    except Exception:  # graftlint: disable=GL004
        pass  # controller mid-restart: fall back to the default policy
    with _routers_lock:
        router = _routers.get(deployment_name)
        if router is not None and deployment_name not in \
                _routers_unresolved:
            return router  # another thread resolved it meanwhile
        if policy == "prefix_aware":
            from ray_tpu.serve.prefix_router import PrefixAwareRouter
            if not isinstance(router, PrefixAwareRouter):
                router = PrefixAwareRouter(deployment_name, controller)
            _routers_unresolved.discard(deployment_name)
        elif policy is not None:
            if router is None:
                router = Router(deployment_name, controller)
            _routers_unresolved.discard(deployment_name)
        else:  # fetch failed: provisional pow-2, retry next call
            if router is None:
                router = Router(deployment_name, controller)
            _routers_unresolved.add(deployment_name)
        _routers[deployment_name] = router
        return router


class DeploymentResponse:
    """Future-like result of handle.remote() (reference:
    serve/handle.py DeploymentResponse).

    The response owns the admission token its router.submit() call
    acquired: result() (or garbage collection of an abandoned
    response) releases it exactly once, so ``inflight`` in the
    AdmissionController tracks truly outstanding requests."""

    def __init__(self, router: Router, method_name: str, args_blob: bytes,
                 replica_id: str, ref):
        import time
        self._router = router
        self._method_name = method_name
        self._args_blob = args_blob
        self._replica_id = replica_id
        self._ref = ref
        self._t_submit = time.monotonic()
        self._released = False

    def _release(self) -> None:
        if not self._released:
            self._released = True
            self._router.admission.release()

    def __del__(self):
        try:
            self._release()
        except Exception:  # graftlint: disable=GL004  # interp teardown
            pass

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import time
        try:
            try:
                value = ray_tpu.get(self._ref, timeout=timeout_s)
            except ray_tpu.exceptions.ActorError:
                # pre_admitted: reuse THIS response's token (released
                # in the outer finally) instead of acquiring a second
                return self._router.fetch(self._method_name,
                                          self._args_blob, timeout_s,
                                          pre_admitted=True)
            if isinstance(value, Rejected):
                # Chosen replica was saturated — re-route with backoff
                # (fetch records its own latency observation).
                return self._router.fetch(self._method_name,
                                          self._args_blob, timeout_s,
                                          pre_admitted=True)
            if isinstance(value, Shed):
                # The handler itself shed (engine saturation): surface
                # as typed, retryable backpressure — never retried
                # automatically, never recorded as latency.
                from ray_tpu.serve.admission import SHED_REQUESTS
                SHED_REQUESTS.inc(tags={
                    "deployment": self._router.deployment_name,
                    "reason": value.reason})
                raise BackpressureError(self._router.deployment_name,
                                        value.retry_after_s,
                                        value.reason)
            self._router.observe_latency(time.monotonic() - self._t_submit)
            return value
        finally:
            self._release()


class DeploymentResponseGenerator:
    """Iterates a streaming deployment response's values (reference:
    serve/handle.py DeploymentResponseGenerator). For a handler that
    returned a plain value, yields that single value."""

    def __init__(self, inner):
        self._inner = inner

    def __iter__(self):
        for _kind, value in self._inner:
            yield value


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__", stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self.method_name = method_name
        self.stream = stream

    def _controller(self):
        return ray_tpu.get_actor(
            __import__("ray_tpu.serve.controller",
                       fromlist=["CONTROLLER_NAME"]).CONTROLLER_NAME)

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                **_ignored) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name or self.method_name,
                                self.stream if stream is None else stream)

    def remote(self, *args, **kwargs):
        router = _get_router(self.deployment_name, self._controller())
        blob = serialization.dumps((args, kwargs))
        if self.stream:
            return DeploymentResponseGenerator(
                router.stream(self.method_name, blob))
        rid, ref = router.submit(self.method_name, blob)
        return DeploymentResponse(router, self.method_name, blob, rid, ref)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name=name, stream=self.stream)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self.method_name,
                 self.stream))
