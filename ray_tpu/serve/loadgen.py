"""Open-loop serve load harness: heavy-tailed traffic against the
full chain (proxy -> router -> replica -> engine).

Reference: the coordinated-omission discipline of wrk2/Lago — an
OPEN-loop generator schedules arrivals from the traffic model alone
and measures each request's latency from its *scheduled* arrival
time, never from when a worker thread got around to sending it. A
closed-loop client (send, wait, send) silently self-throttles under
overload and reports fantasy p99s; this one keeps firing and lets the
admission controller do the shedding it exists for.

Traffic model:

- inter-arrival times drawn from poisson (exponential), lognormal, or
  pareto distributions — the latter two heavy-tailed, matching
  production inference traffic where a few clients batch-submit;
- burst episodes: every ``burst_every_s`` of *virtual* (scheduled)
  time, ``burst_len_s`` seconds run at ``burst_factor``x the base
  rate, exercising EWMA overload detection and SLO autoscaling;
- prefix-shared prompt mix: ``prefix_groups`` distinct long prefixes
  with per-request unique suffixes, so a prefix_aware router has
  real affinity structure to exploit;
- mixed model IDs round-robined from ``model_ids``, exercising the
  multiplex LRU when the target handler is ``@multiplexed``.

Outputs offered/achieved req/s, p50/p95/p99 latency, TTFT
percentiles (stream mode), shed rate, and exact peak queue depth
(via ``AdmissionController.take_max_queue_depth``).

CLI (self-deploys an echo app on the local runtime):

    python -m ray_tpu.serve.loadgen --rate 50 --duration 10 \
        --arrival lognormal --burst-factor 4 --json BENCH_serve.json
"""

from __future__ import annotations

import argparse
import json
import math
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

ARRIVALS = ("poisson", "lognormal", "pareto", "uniform")


@dataclass
class LoadgenConfig:
    rate: float = 20.0             # offered req/s (mean)
    duration_s: float = 5.0
    arrival: str = "poisson"       # one of ARRIVALS
    sigma: float = 1.0             # lognormal shape (ln-space stddev)
    pareto_alpha: float = 1.5      # pareto tail index (>1 for finite mean)
    burst_factor: float = 1.0      # >1 enables burst episodes
    burst_every_s: float = 0.0     # virtual-time period between bursts
    burst_len_s: float = 0.0       # burst duration within each period
    prefix_groups: int = 0         # 0 disables prefix-shared prompts
    prefix_len: int = 64
    unique_len: int = 8
    model_ids: Tuple[str, ...] = ()
    stream: bool = False
    concurrency: int = 32          # sender threads (not a rate limiter)
    timeout_s: float = 30.0
    seed: int = 0


@dataclass
class LoadReport:
    offered: int = 0
    ok: int = 0
    shed: int = 0
    errors: int = 0
    duration_s: float = 0.0
    offered_rps: float = 0.0
    achieved_rps: float = 0.0
    p50_ms: Optional[float] = None
    p95_ms: Optional[float] = None
    p99_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    shed_rate: float = 0.0
    max_queue_depth: Optional[int] = None
    retry_after_mean_s: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    def format(self) -> str:
        def ms(v):
            return "-" if v is None else f"{v:8.1f}"
        lines = [
            f"offered   {self.offered:7d} req "
            f"({self.offered_rps:.1f} req/s over {self.duration_s:.2f}s)",
            f"achieved  {self.ok:7d} ok ({self.achieved_rps:.1f} req/s), "
            f"{self.shed} shed ({100 * self.shed_rate:.1f}%), "
            f"{self.errors} errors",
            f"latency   p50 {ms(self.p50_ms)} ms   "
            f"p95 {ms(self.p95_ms)} ms   p99 {ms(self.p99_ms)} ms",
        ]
        if self.ttft_p50_ms is not None:
            lines.append(f"ttft      p50 {ms(self.ttft_p50_ms)} ms   "
                         f"p99 {ms(self.ttft_p99_ms)} ms")
        if self.max_queue_depth is not None:
            lines.append(f"queue     max depth {self.max_queue_depth}")
        if self.retry_after_mean_s is not None:
            lines.append(
                f"backoff   mean Retry-After {self.retry_after_mean_s:.2f}s")
        return "\n".join(lines)


# -- traffic model ----------------------------------------------------------


def _draw_gap(cfg: LoadgenConfig, rng: random.Random) -> float:
    """One inter-arrival gap with mean 1/rate, per the configured
    distribution."""
    mean = 1.0 / max(cfg.rate, 1e-9)
    if cfg.arrival == "poisson":
        return rng.expovariate(1.0 / mean)
    if cfg.arrival == "lognormal":
        # E[lognormal(mu, sigma)] = exp(mu + sigma^2/2) == mean
        mu = math.log(mean) - cfg.sigma ** 2 / 2.0
        return rng.lognormvariate(mu, cfg.sigma)
    if cfg.arrival == "pareto":
        # paretovariate(a) has mean a/(a-1); scale so E[gap] == mean
        a = max(cfg.pareto_alpha, 1.001)
        xm = mean * (a - 1.0) / a
        return xm * rng.paretovariate(a)
    if cfg.arrival == "uniform":
        return mean
    raise ValueError(f"unknown arrival distribution {cfg.arrival!r}; "
                     f"expected one of {ARRIVALS}")


def arrival_offsets(cfg: LoadgenConfig, rng: random.Random):
    """Yield scheduled arrival offsets (seconds from start), forever.
    Burst episodes compress gaps by burst_factor inside windows of
    VIRTUAL time — the schedule itself, not the wall clock — so the
    burst pattern is deterministic for a given seed."""
    t = 0.0
    while True:
        gap = _draw_gap(cfg, rng)
        if (cfg.burst_factor > 1.0 and cfg.burst_every_s > 0.0
                and (t % cfg.burst_every_s) < cfg.burst_len_s):
            gap /= cfg.burst_factor
        t += gap
        yield t


class PromptMix:
    """Request payload generator: prefix-shared prompts + mixed model
    IDs. Every payload carries ``prompt`` (and ``model`` when
    model_ids were configured) so prefix_aware routing and multiplex
    both see realistic structure."""

    _WORDS = ("graft", "mesh", "shard", "tile", "lane", "core", "host",
              "fuse", "pin", "spill")

    def __init__(self, cfg: LoadgenConfig, rng: random.Random):
        self.cfg = cfg
        self._prefixes: List[str] = []
        for g in range(max(0, cfg.prefix_groups)):
            words = [self._WORDS[rng.randrange(len(self._WORDS))]
                     for _ in range(max(1, cfg.prefix_len // 6))]
            self._prefixes.append(f"sys{g}: " + " ".join(words))

    def make(self, seq: int, rng: random.Random) -> Dict[str, Any]:
        payload: Dict[str, Any] = {"seq": seq}
        if self._prefixes:
            prefix = self._prefixes[seq % len(self._prefixes)]
            suffix = "".join(
                chr(ord("a") + rng.randrange(26))
                for _ in range(self.cfg.unique_len))
            payload["prompt"] = f"{prefix} {suffix}"
        if self.cfg.model_ids:
            payload["model"] = self.cfg.model_ids[
                seq % len(self.cfg.model_ids)]
        return payload


# -- senders ----------------------------------------------------------------
#
# A sender takes a payload and returns (outcome, t_first, retry_after):
# outcome in {"ok", "shed", "error"}; t_first is the absolute monotonic
# time of the first response item (TTFT anchor) or None; retry_after is
# the server-suggested backoff on shed, or None.

Sender = Callable[[Dict[str, Any]],
                  Tuple[str, Optional[float], Optional[float]]]


def handle_sender(handle, *, stream: bool = False,
                  timeout_s: float = 30.0) -> Sender:
    """Drive a DeploymentHandle; BackpressureError counts as shed."""
    from ray_tpu.serve.admission import BackpressureError
    h = handle.options(stream=stream) if stream else handle

    def send(payload):
        try:
            if stream:
                t_first = None
                for _ in h.remote(payload):
                    if t_first is None:
                        t_first = time.monotonic()
                return "ok", t_first, None
            h.remote(payload).result(timeout_s=timeout_s)
            return "ok", None, None
        except BackpressureError as exc:
            return "shed", None, exc.retry_after_s

    return send


def http_sender(url: str, *, timeout_s: float = 30.0) -> Sender:
    """Drive the HTTP proxy; 503 counts as shed (Retry-After header
    parsed when present)."""
    import urllib.error
    import urllib.request

    def send(payload):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                first = resp.read(1)
                t_first = time.monotonic() if first else None
                resp.read()
            return "ok", t_first, None
        except urllib.error.HTTPError as exc:
            if exc.code == 503:
                retry_after = None
                try:
                    retry_after = float(exc.headers.get("Retry-After"))
                except (TypeError, ValueError):
                    pass
                exc.read()
                return "shed", None, retry_after
            return "error", None, None
        except (OSError, urllib.error.URLError):
            return "error", None, None

    return send


# -- the harness ------------------------------------------------------------


@dataclass
class _Sample:
    outcome: str
    latency_s: Optional[float] = None
    ttft_s: Optional[float] = None
    retry_after_s: Optional[float] = None


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    pos = (len(sorted_vals) - 1) * q
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def run_load(cfg: LoadgenConfig, sender: Sender,
             admission=None) -> LoadReport:
    """Run the open-loop schedule against ``sender``; returns the
    report. ``admission`` (an AdmissionController) enables exact peak
    queue depth readout — its peak counter is reset at start."""
    rng = random.Random(cfg.seed)
    mix = PromptMix(cfg, rng)
    # Payload randomness comes from a second stream so arrival draws
    # stay identical whether or not prompts are enabled.
    payload_rng = random.Random(cfg.seed + 1)
    work: "queue.Queue" = queue.Queue()
    samples: List[_Sample] = []
    samples_lock = threading.Lock()

    if admission is not None:
        admission.take_max_queue_depth()  # reset the peak counter

    def worker():
        while True:
            item = work.get()
            if item is None:
                return
            t_sched, payload = item
            try:
                outcome, t_first, retry_after = sender(payload)
            except Exception:  # noqa: BLE001 — one bad request != abort
                outcome, t_first, retry_after = "error", None, None
            t_end = time.monotonic()
            s = _Sample(outcome=outcome, retry_after_s=retry_after)
            if outcome == "ok":
                # Latency anchored at the SCHEDULED arrival, so time a
                # request spent waiting for a free sender thread (i.e.
                # the overload we induced) is charged to the system.
                s.latency_s = max(0.0, t_end - t_sched)
                if t_first is not None:
                    s.ttft_s = max(0.0, t_first - t_sched)
            with samples_lock:
                samples.append(s)

    workers = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, cfg.concurrency))]
    for w in workers:
        w.start()

    offered = 0
    t_start = time.monotonic()
    for offset in arrival_offsets(cfg, rng):
        if offset > cfg.duration_s:
            break
        t_fire = t_start + offset
        delay = t_fire - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        work.put((t_fire, mix.make(offered, payload_rng)))
        offered += 1
    for _ in workers:
        work.put(None)
    deadline = time.monotonic() + cfg.timeout_s + 5.0
    for w in workers:
        w.join(timeout=max(0.1, deadline - time.monotonic()))
    wall_s = time.monotonic() - t_start

    peak_depth = (admission.take_max_queue_depth()
                  if admission is not None else None)
    with samples_lock:
        done = list(samples)
    return _build_report(cfg, done, offered, wall_s, peak_depth)


def _build_report(cfg: LoadgenConfig, samples: List[_Sample],
                  offered: int, wall_s: float,
                  peak_depth: Optional[int]) -> LoadReport:
    ok = [s for s in samples if s.outcome == "ok"]
    shed = [s for s in samples if s.outcome == "shed"]
    errors = [s for s in samples if s.outcome == "error"]
    lat = sorted(s.latency_s for s in ok if s.latency_s is not None)
    ttft = sorted(s.ttft_s for s in ok if s.ttft_s is not None)
    retry = [s.retry_after_s for s in shed if s.retry_after_s is not None]
    finished = max(1, len(samples))
    r = LoadReport(
        offered=offered, ok=len(ok), shed=len(shed), errors=len(errors),
        duration_s=wall_s,
        offered_rps=offered / max(wall_s, 1e-9),
        achieved_rps=len(ok) / max(wall_s, 1e-9),
        shed_rate=len(shed) / finished,
        max_queue_depth=peak_depth)
    for name, q in (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99)):
        v = _percentile(lat, q)
        setattr(r, name, None if v is None else v * 1000.0)
    for name, q in (("ttft_p50_ms", 0.50), ("ttft_p99_ms", 0.99)):
        v = _percentile(ttft, q)
        setattr(r, name, None if v is None else v * 1000.0)
    if retry:
        r.retry_after_mean_s = sum(retry) / len(retry)
    return r


# -- CLI: self-deployed echo app --------------------------------------------


class EchoServer:
    """Minimal handler for CLI runs: optional simulated work, echoes
    the model id back so multiplex mixes are visible in responses.
    Module-level so replica actors can unpickle it by reference."""

    def __init__(self, work_ms: float = 0.0):
        self.work_ms = float(work_ms)

    def __call__(self, request: Optional[Dict[str, Any]] = None):
        if self.work_ms > 0.0:
            time.sleep(self.work_ms / 1000.0)
        request = request or {}
        return {"ok": True, "seq": request.get("seq"),
                "model": request.get("model")}


def _bench_record(cfg: LoadgenConfig, report: LoadReport) -> Dict[str, Any]:
    parsed = [
        {"metric": "serve_req_per_s", "value": round(report.achieved_rps, 2),
         "unit": "req/s"},
        {"metric": "serve_shed_rate", "value": round(report.shed_rate, 4),
         "unit": "fraction"},
    ]
    if report.p99_ms is not None:
        parsed.insert(1, {"metric": "serve_p99_latency",
                          "value": round(report.p99_ms, 2), "unit": "ms"})
    return {
        "bench": "serve_loadgen",
        "config": dict(cfg.__dict__),
        "report": report.to_dict(),
        "parsed": parsed,
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.serve.loadgen",
        description="Open-loop load harness for ray_tpu.serve")
    p.add_argument("--rate", type=float, default=20.0)
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--arrival", choices=ARRIVALS, default="poisson")
    p.add_argument("--sigma", type=float, default=1.0)
    p.add_argument("--pareto-alpha", type=float, default=1.5)
    p.add_argument("--burst-factor", type=float, default=1.0)
    p.add_argument("--burst-every", type=float, default=0.0)
    p.add_argument("--burst-len", type=float, default=0.0)
    p.add_argument("--prefix-groups", type=int, default=0)
    p.add_argument("--model-ids", default="",
                   help="comma-separated model ids to round-robin")
    p.add_argument("--stream", action="store_true")
    p.add_argument("--concurrency", type=int, default=32)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--url", default=None,
                   help="hit an existing HTTP proxy instead of "
                        "self-deploying an echo app")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--max-ongoing", type=int, default=8)
    p.add_argument("--max-queued", type=int, default=64)
    p.add_argument("--work-ms", type=float, default=2.0,
                   help="simulated handler work (self-deploy mode)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write a BENCH_serve.json-style record here")
    args = p.parse_args(argv)

    cfg = LoadgenConfig(
        rate=args.rate, duration_s=args.duration, arrival=args.arrival,
        sigma=args.sigma, pareto_alpha=args.pareto_alpha,
        burst_factor=args.burst_factor, burst_every_s=args.burst_every,
        burst_len_s=args.burst_len, prefix_groups=args.prefix_groups,
        model_ids=tuple(m for m in args.model_ids.split(",") if m),
        stream=args.stream, concurrency=args.concurrency, seed=args.seed)

    if args.url:
        sender = http_sender(args.url)
        report = run_load(cfg, sender)
    else:
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.serve.admission import get_admission_controller
        # Under ``python -m`` this file runs as __main__; pick up the
        # canonical import of EchoServer so replicas can unpickle it
        # by reference.
        from ray_tpu.serve.loadgen import EchoServer as _Echo
        # The implicit init sizes the pool from os.cpu_count(); on a
        # small box that can be fewer slots than replicas, which would
        # leave the deployment UPDATING forever.
        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=max(4, args.replicas + 1))
        router = "prefix_aware" if cfg.prefix_groups else "pow2"
        dep = serve.deployment(
            name="loadgen_echo", num_replicas=args.replicas,
            max_ongoing_requests=args.max_ongoing,
            max_queued_requests=args.max_queued,
            request_router=router)(_Echo)
        handle = serve.run(dep.bind(args.work_ms), name="loadgen")
        try:
            # Warm the router/admission config before measuring.
            handle.remote({"seq": -1}).result(timeout_s=30)
            sender = handle_sender(handle, stream=cfg.stream,
                                   timeout_s=cfg.timeout_s)
            admission = get_admission_controller("loadgen_echo")
            report = run_load(cfg, sender, admission=admission)
        finally:
            try:
                serve.shutdown()
                ray_tpu.shutdown()
            except Exception:  # graftlint: disable=GL004  # teardown
                pass

    print(report.format())
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(_bench_record(cfg, report), f, indent=2)
            f.write("\n")
        print(f"wrote {args.json_path}")
    return 0 if report.errors == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
