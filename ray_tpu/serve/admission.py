"""Admission control and backpressure for the serve chain.

Reference: python/ray/serve/_private/router.py max_queued_requests +
BackPressureError; the Sebulba podracer pattern (PAPERS.md) — request
sources feed batched TPU inference through explicitly BOUNDED queues,
never unbounded ones. Under overload the right answer is to shed at the
front door (cheap: one dict lookup in the driver) instead of queueing
work that will blow the latency SLO anyway.

One ``AdmissionController`` lives driver-side per deployment (next to
the router, which owns the actual dispatch). Semantics:

- ``inflight``  — requests admitted and not yet finished.
- ``capacity``  — live_replicas * max_ongoing_requests (refreshed by
  the router whenever it learns of a replica-set change).
- ``queued``    — max(0, inflight - capacity): requests the replicas
  cannot be executing right now, i.e. true queue depth.
- admit iff ``queued < max_queued_requests`` (cap < 0 disables the
  cap). A cap of 0 sheds the moment every replica slot is full; a cap
  of 1 lets exactly one request wait.
- EWMA overload detection: when the exponentially-decayed queue-wait
  (fed by the PR-1 ``ray_tpu_serve_queue_wait_seconds`` observations)
  exceeds ``shed_queue_wait_s``, new arrivals shed even under the hard
  cap — queue wait rises before queue depth saturates.

Shed requests raise ``BackpressureError`` BEFORE any latency histogram
observation, so p50/p99 reflect served traffic only; sheds are counted
separately in ``ray_tpu_serve_shed_requests_total``.
"""

from __future__ import annotations

import math

from ray_tpu.devtools import locktrace
import time
from typing import Dict, Optional

from ray_tpu.util.metrics import Counter, Gauge

SHED_REQUESTS = Counter(
    "ray_tpu_serve_shed_requests_total",
    "Requests shed by admission control, by deployment and reason",
    tag_keys=("deployment", "reason"))
ADMISSION_QUEUED = Gauge(
    "ray_tpu_serve_admission_queued_requests",
    "Requests admitted beyond replica capacity (true queue depth)",
    tag_keys=("deployment",))
ADMISSION_INFLIGHT = Gauge(
    "ray_tpu_serve_admission_inflight_requests",
    "Requests admitted and not yet finished, by deployment",
    tag_keys=("deployment",))


class BackpressureError(RuntimeError):
    """Raised on the handle path when admission control sheds a
    request (the HTTP proxy translates it to 503 + ``Retry-After``).
    ``retryable`` is True by definition: the request was never
    executed, so resubmitting after ``retry_after_s`` is always safe.
    """

    retryable = True

    def __init__(self, deployment: str, retry_after_s: float = 1.0,
                 reason: str = "queue_full"):
        self.deployment = deployment
        self.retry_after_s = float(retry_after_s)
        self.reason = reason
        super().__init__(
            f"deployment {deployment!r} is overloaded ({reason}); "
            f"retry after {self.retry_after_s:.2f}s")

    def __reduce__(self):
        return (BackpressureError,
                (self.deployment, self.retry_after_s, self.reason))


class Shed:
    """Sentinel RETURNED by a replica whose handler shed the request
    (e.g. the LLM engine's reject-before-enqueue hook). Like
    ``Rejected`` it travels the wire as a value, not a raised error —
    but unlike Rejected the router must NOT retry another replica: the
    handler itself declared overload, so the verdict goes straight back
    to the client as backpressure."""

    def __init__(self, retry_after_s: float = 1.0,
                 reason: str = "saturated"):
        self.retry_after_s = float(retry_after_s)
        self.reason = reason

    def __reduce__(self):
        return (Shed, (self.retry_after_s, self.reason))


class _Ewma:
    """Irregular-interval EWMA: the previous value's weight decays by
    elapsed wall time (half-life semantics), so a burst five minutes
    ago doesn't read as current overload."""

    def __init__(self, halflife_s: float):
        self.halflife_s = halflife_s
        self._value = 0.0
        self._t = None  # type: Optional[float]

    def update(self, sample: float, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        if self._t is None:
            self._value = float(sample)
        else:
            w = 0.5 ** (max(0.0, now - self._t) / self.halflife_s)
            self._value = w * self._value + (1.0 - w) * float(sample)
        self._t = now
        return self._value

    def value(self, now: Optional[float] = None) -> float:
        """Read WITH decay toward zero: silence is evidence of recovery,
        not of the last observed value persisting forever."""
        if self._t is None:
            return 0.0
        now = time.monotonic() if now is None else now
        return self._value * 0.5 ** (max(0.0, now - self._t)
                                     / self.halflife_s)


class AdmissionController:
    """Per-deployment admission state (driver-side, shared by every
    entry path of that deployment's router)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = locktrace.traced_lock("serve.admission")
        self._inflight = 0
        self._capacity = 1
        self._max_queued = -1        # < 0: cap disabled
        self._shed_queue_wait_s = 0.0  # <= 0: EWMA shedding disabled
        self._queue_wait = _Ewma(halflife_s=2.0)
        self._latency = _Ewma(halflife_s=5.0)
        self._total = 0
        self._shed_total = 0
        self._max_queued_seen = 0

    # -- configuration (router refresh path) --

    def configure(self, *, max_queued: Optional[int] = None,
                  capacity: Optional[int] = None,
                  shed_queue_wait_s: Optional[float] = None) -> None:
        with self._lock:
            if max_queued is not None:
                self._max_queued = int(max_queued)
            if capacity is not None:
                self._capacity = max(1, int(capacity))
            if shed_queue_wait_s is not None:
                self._shed_queue_wait_s = float(shed_queue_wait_s)

    # -- request path --

    def try_acquire(self) -> None:
        """Admit or raise BackpressureError. Must be paired with
        exactly one release() when admitted."""
        now = time.monotonic()
        with self._lock:
            reason = None
            # admit iff inflight < capacity + cap: with the cap at 0
            # a request sheds exactly when every replica slot is busy;
            # cap 1 lets one request wait, and so on
            if (self._max_queued >= 0
                    and self._inflight
                    >= self._capacity + self._max_queued):
                reason = "queue_full"
            elif (self._shed_queue_wait_s > 0.0
                  and self._queue_wait.value(now)
                  > self._shed_queue_wait_s):
                reason = "queue_wait_ewma"
            if reason is None:
                self._inflight += 1
                self._total += 1
                queued_after = max(0, self._inflight - self._capacity)
                self._max_queued_seen = max(self._max_queued_seen,
                                            queued_after)
                inflight = self._inflight
            else:
                self._shed_total += 1
                retry_after = self._retry_after_locked(now)
        if reason is None:
            ADMISSION_INFLIGHT.set(
                float(inflight),
                tags={"deployment": self.deployment_name})
            ADMISSION_QUEUED.set(
                float(queued_after),
                tags={"deployment": self.deployment_name})
            return
        SHED_REQUESTS.inc(tags={"deployment": self.deployment_name,
                                "reason": reason})
        raise BackpressureError(self.deployment_name, retry_after,
                                reason)

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            inflight = self._inflight
            queued = max(0, inflight - self._capacity)
        ADMISSION_INFLIGHT.set(float(inflight),
                               tags={"deployment": self.deployment_name})
        ADMISSION_QUEUED.set(float(queued),
                             tags={"deployment": self.deployment_name})

    # -- signal feeds (router observation path) --

    def note_queue_wait(self, seconds: float) -> None:
        with self._lock:
            self._queue_wait.update(seconds)

    def note_latency(self, seconds: float) -> None:
        with self._lock:
            self._latency.update(seconds)

    # -- readouts --

    def queue_depth(self) -> int:
        with self._lock:
            return max(0, self._inflight - self._capacity)

    def take_max_queue_depth(self) -> int:
        """Peak queue depth since the last call (and reset) — load
        harness runs use this to report exact per-window peaks instead
        of a sampled approximation."""
        with self._lock:
            peak = self._max_queued_seen
            self._max_queued_seen = max(
                0, self._inflight - self._capacity)
            return peak

    def _retry_after_locked(self, now: float) -> float:
        # How long until a shed client's retry plausibly finds room:
        # roughly one queue's worth of service time, floored so clients
        # never busy-spin and capped so they never give up for minutes.
        latency = self._latency.value(now)
        queued = max(0, self._inflight - self._capacity)
        per_slot = latency / max(1, self._capacity)
        estimate = max(0.1, per_slot * (queued + 1))
        return min(30.0, estimate if math.isfinite(estimate) else 1.0)

    def snapshot(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            queued = max(0, self._inflight - self._capacity)
            return {
                "inflight": float(self._inflight),
                "capacity": float(self._capacity),
                "queue_depth": float(queued),
                "max_queue_depth": float(self._max_queued_seen),
                "ewma_queue_wait_s": self._queue_wait.value(now),
                "ewma_latency_s": self._latency.value(now),
                "total": float(self._total),
                "shed_total": float(self._shed_total),
            }


_controllers: Dict[str, AdmissionController] = {}
_controllers_lock = locktrace.traced_lock("serve.admission.registry")


def get_admission_controller(deployment_name: str) -> AdmissionController:
    with _controllers_lock:
        ctrl = _controllers.get(deployment_name)
        if ctrl is None:
            ctrl = AdmissionController(deployment_name)
            _controllers[deployment_name] = ctrl
        return ctrl


def reset_admission() -> None:
    """Forget all per-deployment admission state (serve.shutdown)."""
    with _controllers_lock:
        _controllers.clear()
