"""ray_tpu.serve — scalable model serving on the core runtime.

Capability parity with Ray Serve (reference: python/ray/serve/ —
controller + replicas + router + proxy, autoscaling, batching,
multiplexing, composition via handles).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.admission import BackpressureError
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import (
    Application,
    Deployment,
    deployment,
    flatten_application,
)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

_proxy = None
_grpc_proxy = None


def _get_or_start_controller():
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        Controller = ray_tpu.remote(ServeController)
        handle = Controller.options(
            name=CONTROLLER_NAME, max_concurrency=8, num_cpus=0).remote()
        ray_tpu.get(handle.ping.remote())
        return handle


def start(http_options: Optional[HTTPOptions] = None,
          proxy: bool = False, grpc_port: Optional[int] = None):
    """Start the serve control plane (and optionally the HTTP proxy
    and/or the gRPC ingress — reference: serve's HTTP + gRPC proxies,
    serve/_private/proxy.py:530,706)."""
    global _proxy, _grpc_proxy
    controller = _get_or_start_controller()
    if proxy and _proxy is None:
        from ray_tpu.serve.proxy import HttpProxy
        opts = http_options or HTTPOptions()
        _proxy = HttpProxy(controller, opts.host, opts.port)
    if grpc_port is not None and _grpc_proxy is None:
        from ray_tpu.serve.grpc_proxy import GrpcProxy
        _grpc_proxy = GrpcProxy(controller, port=grpc_port)
    return controller


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking_ready: bool = True,
        timeout_s: float = 60.0, local_testing_mode: bool = False):
    """Deploy an application; returns the ingress handle
    (reference: python/ray/serve/api.py serve.run:694).

    ``local_testing_mode=True`` instantiates the whole deployment
    graph in-process — no controller, no cluster, no ray_tpu.init —
    and returns a handle with DeploymentHandle semantics (reference:
    serve/_private/local_testing_mode.py:49)."""
    if local_testing_mode:
        from ray_tpu.serve.local_mode import run_local
        return run_local(app)
    controller = _get_or_start_controller()
    specs = flatten_application(app, name, route_prefix)
    ray_tpu.get(controller.deploy_application.remote(name, specs))
    ingress = app.deployment.name
    if blocking_ready:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = ray_tpu.get(controller.get_status.remote())
            d = status.get(ingress)
            if d and d["status"] == "HEALTHY" and d["running_replicas"] > 0:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError(f"deployment {ingress} not ready "
                               f"after {timeout_s}s: {status}")
    return DeploymentHandle(ingress, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_or_start_controller()
    status = ray_tpu.get(controller.get_status.remote())
    for dep, info in status.items():
        if info["app"] == name and info["route_prefix"]:
            return DeploymentHandle(dep, name)
    raise ValueError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> Dict[str, dict]:
    controller = _get_or_start_controller()
    return ray_tpu.get(controller.get_status.remote())


def delete(name: str) -> None:
    controller = _get_or_start_controller()
    ray_tpu.get(controller.delete_application.remote(name))


def shutdown() -> None:
    global _proxy, _grpc_proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    if _grpc_proxy is not None:
        _grpc_proxy.stop()
        _grpc_proxy = None
    if not ray_tpu.is_initialized():
        return
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except ValueError:
        pass
    from ray_tpu.serve import handle as handle_mod
    with handle_mod._routers_lock:
        handle_mod._routers.clear()
        handle_mod._routers_unresolved.clear()
    from ray_tpu.serve.admission import reset_admission
    reset_admission()


__all__ = [
    "Application", "AutoscalingConfig", "BackpressureError",
    "Deployment", "DeploymentConfig",
    "DeploymentHandle", "DeploymentResponse", "HTTPOptions", "batch",
    "delete", "deploy_config", "deploy_config_file", "deployment",
    "get_app_handle", "get_deployment_handle",
    "get_multiplexed_model_id", "multiplexed", "run", "shutdown", "start",
    "status",
]


def deploy_config(config):
    """Apply a declarative application config dict (reference:
    serve/schema.py ServeDeploySchema + REST deploy)."""
    from ray_tpu.serve.schema import deploy_config as _deploy
    return _deploy(config)


def deploy_config_file(path: str):
    from ray_tpu.serve.schema import deploy_config_file as _deploy_file
    return _deploy_file(path)
