"""Request router: power-of-two-choices replica selection.

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:496 AsyncioRouter;
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter —
queue-length probes, retry on rejection, replica-set refresh through the
controller's long-poll).

Every public entry path (submit/fetch/stream) passes through the
deployment's AdmissionController first (ray_tpu/serve/admission.py):
overload sheds with a typed BackpressureError BEFORE any replica RPC
and before any latency observation, so queues stay bounded and the
latency histograms describe served traffic only.
"""

from __future__ import annotations

import logging
import math
import random
import threading

from ray_tpu.devtools import locktrace
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.admission import (
    BackpressureError, SHED_REQUESTS, Shed, get_admission_controller)
from ray_tpu.serve.replica import Rejected
from ray_tpu.util import tracing
from ray_tpu.util.metrics import (
    Counter, Histogram, percentile_from_counts)

logger = logging.getLogger(__name__)

_PROBE_CACHE_S = 0.1
# how often a busy router pushes its admission snapshot (queue depth,
# windowed p99) to the controller for the SLO autoscaling policy
_SLO_REPORT_INTERVAL_S = 0.25

# Per-deployment router instrumentation (reference: serve request
# metrics surfaced for autoscaling + dashboards). Queue wait is the
# admission delay a request spends being rejected/re-routed before a
# replica accepts it.
ROUTER_REQUESTS = Counter(
    "ray_tpu_serve_router_requests_total",
    "Requests routed, by deployment", tag_keys=("deployment",))
ROUTER_REJECTIONS = Counter(
    "ray_tpu_serve_router_rejections_total",
    "Replica rejections seen while routing", tag_keys=("deployment",))
REQUEST_LATENCY = Histogram(
    "ray_tpu_serve_request_latency_seconds",
    "End-to-end request latency through the router",
    tag_keys=("deployment",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0])
QUEUE_WAIT = Histogram(
    "ray_tpu_serve_queue_wait_seconds",
    "Admission delay before a replica accepted the request",
    tag_keys=("deployment",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0])


class Router:
    # Rejection-penalty half-life: a replica's penalty score decays by
    # e^(-elapsed/tau), so a replica that STOPS rejecting drifts back
    # to zero and regains affinity traffic (tests shrink this to
    # exercise recovery without real waiting).
    reject_penalty_tau_s = 2.0
    # decayed scores below this round to zero (and drop their entry)
    _REJECT_PENALTY_FLOOR = 0.05

    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self.controller = controller
        self.admission = get_admission_controller(deployment_name)
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []
        self._qlen_cache: Dict[str, Tuple[float, int]] = {}
        # replicas that recently rejected requests sit out affinity-
        # based selection (content routers consult this so a saturated
        # cache-affine replica can't livelock retries while others
        # idle); pow-2 probing ignores it. rid -> (score, t_updated):
        # the score grows by 1 per rejection and decays exponentially.
        self._reject_penalty: Dict[str, Tuple[float, float]] = {}
        self._lock = locktrace.traced_lock("serve.router")
        self._rng = random.Random()
        self._last_slo_report = 0.0
        # REQUEST_LATENCY bucket counts at the last SLO report; the
        # delta between consecutive snapshots yields a WINDOWED p99
        # (a lifetime histogram never forgets a slow warm-up)
        self._latency_window: Optional[list] = None

    def _refresh(self, block: bool) -> None:
        if block:
            version, replicas = ray_tpu.get(
                self.controller.poll_replicas.remote(
                    self.deployment_name, self._version, 2.0))
        else:
            version, replicas = ray_tpu.get(
                self.controller.get_replicas.remote(self.deployment_name))
        with self._lock:
            self._version = version
            self._replicas = replicas
        # admission capacity tracks the live replica set; the knobs
        # come from the deployment config held by the controller
        try:
            cfg = ray_tpu.get(self.controller.get_admission_config.remote(
                self.deployment_name), timeout=5)
            self.admission.configure(
                max_queued=cfg["max_queued_requests"],
                capacity=max(1, len(replicas))
                * max(1, cfg["max_ongoing_requests"]),
                shed_queue_wait_s=cfg["shed_queue_wait_s"])
        except Exception:
            logger.debug("admission config fetch failed for %r "
                         "(controller restarting?)",
                         self.deployment_name, exc_info=True)

    # -- rejection penalty (EWMA with decay toward zero) --

    def _note_rejection_locked(self, rid: str) -> None:
        # caller holds self._lock (the _locked suffix is the contract)
        now = time.monotonic()
        score = self._decayed_penalty_locked(rid, now) + 1.0
        self._reject_penalty[rid] = (score, now)  # graftlint: disable=GL001

    def _decayed_penalty_locked(self, rid: str, now: float) -> float:
        entry = self._reject_penalty.get(rid)
        if entry is None:
            return 0.0
        score, t = entry
        value = score * math.exp(-(now - t) / self.reject_penalty_tau_s)
        if value < self._REJECT_PENALTY_FLOOR:
            self._reject_penalty.pop(rid, None)  # graftlint: disable=GL001
            return 0.0
        return value

    def rejection_penalty(self, rid: str) -> float:
        """Current (decayed) rejection-penalty score for a replica.
        0.0 means fully recovered; content-affinity policies skip a
        replica whose score is still >= 1 (one undecayed rejection)."""
        with self._lock:
            return self._decayed_penalty_locked(rid, time.monotonic())

    # -- SLO stats push (feeds the controller's "slo" policy) --

    def _maybe_report_slo(self) -> None:
        if self.controller is None:
            return
        now = time.monotonic()
        if now - self._last_slo_report < _SLO_REPORT_INTERVAL_S:
            return
        self._last_slo_report = now
        snap = self.admission.snapshot()
        p99 = 0.0
        cur = REQUEST_LATENCY.snapshot(
            tags={"deployment": self.deployment_name})
        if cur is not None:
            bounds, buckets, _total, _count = cur
            prev = self._latency_window
            window = ([b - p for b, p in zip(buckets, prev)]
                      if prev is not None and len(prev) == len(buckets)
                      else buckets)
            self._latency_window = buckets
            value = percentile_from_counts(bounds, window, 0.99)
            if value is not None:
                p99 = value
        snap["p99_latency_s"] = p99
        try:
            # fire-and-forget: the reconcile loop reads it next tick
            self.controller.report_slo_stats.remote(
                self.deployment_name, snap)
            # piggyback a cheap replica-set refresh so capacity (and
            # routing) track autoscaler-added replicas under load
            self._refresh(block=False)
        except Exception:
            logger.debug("SLO stats push failed for %r",
                         self.deployment_name, exc_info=True)

    def _queue_len(self, rid: str, handle) -> int:
        now = time.monotonic()
        cached = self._qlen_cache.get(rid)
        if cached and now - cached[0] < _PROBE_CACHE_S:
            return cached[1]
        try:
            qlen = ray_tpu.get(handle.get_queue_len.remote(), timeout=1.0)
        except Exception:
            qlen = 1 << 30  # unprobeable replica loses the comparison
        with self._lock:
            self._qlen_cache[rid] = (now, qlen)
        return qlen

    def choose(self, args_blob: Optional[bytes] = None
               ) -> Tuple[str, Any]:
        """Pick a replica: two random candidates, shorter queue wins.
        ``args_blob`` (the serialized request) is ignored here but lets
        policy subclasses route on request CONTENT (prefix_router.py);
        retries re-enter choose() with the same blob, so content
        policies re-apply on every attempt."""
        deadline = time.monotonic() + 30.0
        block = False
        while True:
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                if len(replicas) == 1:
                    return replicas[0]
                a, b = self._rng.sample(replicas, 2)
                return a if (self._queue_len(*a) <= self._queue_len(*b)) \
                    else b
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment "
                    f"{self.deployment_name!r} after 30 s")
            self._refresh(block)
            block = True

    def submit(self, method_name: str, args_blob: bytes):
        """Route once and return (replica_id, ObjectRef); rejection is
        surfaced at get() time and retried by DeploymentResponse.
        Admission happens HERE (raises BackpressureError when shed);
        the matching release is DeploymentResponse's duty."""
        self.admission.try_acquire()
        try:
            self._maybe_report_slo()
            ROUTER_REQUESTS.inc(tags={"deployment": self.deployment_name})
            with tracing.span("route", component="serve.router",
                              tags={"deployment": self.deployment_name}):
                rid, handle = self.choose(args_blob)
                return rid, handle.handle_request.remote(method_name,
                                                         args_blob)
        except BaseException:
            self.admission.release()  # routing failed: token back
            raise

    def observe_latency(self, seconds: float) -> None:
        """Record one finished request's latency (called by
        DeploymentResponse.result, where the handle path's wait ends)."""
        REQUEST_LATENCY.observe(seconds,
                                tags={"deployment": self.deployment_name})
        self.admission.note_latency(seconds)

    def _admit_stream(self, method_name: str, args_blob: bytes,
                      item_timeout_s: Optional[float]):
        """Route a streaming request until a replica admits it; returns
        (t0, kind, header, item_iterator). Runs under a routing span so
        the replica's actor task attaches to the request's trace;
        metrics cover admission (queue wait) and rejections. A "shed"
        header (the handler itself declared overload) raises
        BackpressureError instead of retrying — the verdict is about
        the workload, not one replica's slot count."""
        t0 = time.monotonic()
        attempts = 0
        deadline = t0 + 60.0
        dep_tags = {"deployment": self.deployment_name}
        ROUTER_REQUESTS.inc(tags=dep_tags)
        with tracing.span("route", component="serve.router",
                          tags=dep_tags):
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"streaming request to {self.deployment_name} "
                        f"not admitted after {attempts} rejected "
                        "attempts")
                rid, handle = self.choose(args_blob)
                it = handle.handle_request_streaming.options(
                    num_returns="streaming").remote(method_name,
                                                    args_blob)
                try:
                    header = ray_tpu.get(it.next_ready(item_timeout_s),
                                         timeout=item_timeout_s)
                except StopIteration:
                    self._refresh(block=False)
                    continue
                except ray_tpu.exceptions.ActorError:
                    self._refresh(block=False)
                    continue
                kind = header.get("type")
                if kind == "rejected":
                    attempts += 1
                    ROUTER_REJECTIONS.inc(tags=dep_tags)
                    with self._lock:
                        self._qlen_cache.pop(rid, None)
                        self._note_rejection_locked(rid)
                    time.sleep(min(0.05 * attempts, 0.5))
                    continue
                if kind == "shed":
                    SHED_REQUESTS.inc(tags={
                        "deployment": self.deployment_name,
                        "reason": header.get("reason", "saturated")})
                    raise BackpressureError(
                        self.deployment_name,
                        header.get("retry_after_s", 1.0),
                        header.get("reason", "saturated"))
                wait = time.monotonic() - t0
                QUEUE_WAIT.observe(wait, tags=dep_tags)
                self.admission.note_queue_wait(wait)
                return t0, kind, header, it

    def stream(self, method_name: str, args_blob: bytes,
               item_timeout_s: Optional[float] = None):
        """Route a streaming request (reference: router streaming path,
        serve/_private/router.py handle streaming). Returns an iterator
        of the replica's items after the header: a single
        ("single", value) item, or ("chunk", value) items as the
        handler produces them. Re-routes on rejection/replica death
        before any chunk was consumed. Raises BackpressureError AT CALL
        TIME when admission sheds (no generator is created, no latency
        is recorded)."""
        self.admission.try_acquire()
        try:
            self._maybe_report_slo()
            t0, kind, header, it = self._admit_stream(
                method_name, args_blob, item_timeout_s)
        except BaseException:
            self.admission.release()
            raise
        return self._consume_stream(t0, kind, header, it, item_timeout_s)

    def _consume_stream(self, t0: float, kind: str, header: dict, it,
                        item_timeout_s: Optional[float]):
        dep_tags = {"deployment": self.deployment_name}
        try:
            if kind == "single":
                latency = time.monotonic() - t0
                REQUEST_LATENCY.observe(latency, tags=dep_tags)
                self.admission.note_latency(latency)
                yield "single", header.get("data")
                return
            try:
                while True:
                    try:
                        ref = it.next_ready(item_timeout_s)
                    except StopIteration:
                        return
                    item = ray_tpu.get(ref, timeout=item_timeout_s)
                    yield "chunk", item.get("data")
            finally:
                latency = time.monotonic() - t0
                REQUEST_LATENCY.observe(latency, tags=dep_tags)
                self.admission.note_latency(latency)
        finally:
            self.admission.release()

    def fetch(self, method_name: str, args_blob: bytes,
              timeout: Optional[float],
              pre_admitted: bool = False) -> Any:
        """Route + get with rejection retries (the blocking path).
        ``pre_admitted=True`` reuses a token the caller already holds
        (DeploymentResponse re-routing a rejected submit) instead of
        acquiring — and releasing — a second one."""
        acquired = False
        if not pre_admitted:
            self.admission.try_acquire()
            acquired = True
        try:
            return self._fetch_admitted(method_name, args_blob, timeout)
        finally:
            if acquired:
                self.admission.release()

    def _fetch_admitted(self, method_name: str, args_blob: bytes,
                        timeout: Optional[float]) -> Any:
        self._maybe_report_slo()
        t0 = time.monotonic()
        attempts = 0
        deadline = (t0 + timeout) if timeout else None
        dep_tags = {"deployment": self.deployment_name}
        ROUTER_REQUESTS.inc(tags=dep_tags)
        with tracing.span("route", component="serve.router",
                          tags=dep_tags):
            while True:
                t_attempt = time.monotonic()
                rid, handle = self.choose(args_blob)
                ref = handle.handle_request.remote(method_name, args_blob)
                try:
                    remaining = (max(0.001, deadline - time.monotonic())
                                 if deadline else None)
                    result = ray_tpu.get(ref, timeout=remaining)
                except ray_tpu.exceptions.ActorError:
                    self._refresh(block=False)  # replica died; new set
                    continue
                if isinstance(result, Shed):
                    SHED_REQUESTS.inc(tags={
                        "deployment": self.deployment_name,
                        "reason": result.reason})
                    raise BackpressureError(self.deployment_name,
                                            result.retry_after_s,
                                            result.reason)
                if not isinstance(result, Rejected):
                    wait = t_attempt - t0
                    QUEUE_WAIT.observe(wait, tags=dep_tags)
                    self.admission.note_queue_wait(wait)
                    latency = time.monotonic() - t0
                    REQUEST_LATENCY.observe(latency, tags=dep_tags)
                    self.admission.note_latency(latency)
                    return result
                attempts += 1
                ROUTER_REJECTIONS.inc(tags=dep_tags)
                with self._lock:
                    self._qlen_cache.pop(rid, None)
                    self._note_rejection_locked(rid)
                if deadline and time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"request to {self.deployment_name} timed out "
                        f"after {attempts} rejected attempts")
                time.sleep(min(0.05 * attempts, 0.5))
