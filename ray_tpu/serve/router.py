"""Request router: power-of-two-choices replica selection.

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:496 AsyncioRouter;
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter —
queue-length probes, retry on rejection, replica-set refresh through the
controller's long-poll).
"""

from __future__ import annotations

import random
import threading

from ray_tpu.devtools import locktrace
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.replica import Rejected
from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Histogram

_PROBE_CACHE_S = 0.1

# Per-deployment router instrumentation (reference: serve request
# metrics surfaced for autoscaling + dashboards). Queue wait is the
# admission delay a request spends being rejected/re-routed before a
# replica accepts it.
ROUTER_REQUESTS = Counter(
    "ray_tpu_serve_router_requests_total",
    "Requests routed, by deployment", tag_keys=("deployment",))
ROUTER_REJECTIONS = Counter(
    "ray_tpu_serve_router_rejections_total",
    "Replica rejections seen while routing", tag_keys=("deployment",))
REQUEST_LATENCY = Histogram(
    "ray_tpu_serve_request_latency_seconds",
    "End-to-end request latency through the router",
    tag_keys=("deployment",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0])
QUEUE_WAIT = Histogram(
    "ray_tpu_serve_queue_wait_seconds",
    "Admission delay before a replica accepted the request",
    tag_keys=("deployment",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0])


class Router:
    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self.controller = controller
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []
        self._qlen_cache: Dict[str, Tuple[float, int]] = {}
        # replicas that just rejected a request sit out affinity-based
        # selection for a beat (content routers consult this so a
        # saturated cache-affine replica can't livelock retries while
        # others idle); pow-2 probing ignores it.
        self._reject_penalty: Dict[str, float] = {}
        self._lock = locktrace.traced_lock("serve.router")
        self._rng = random.Random()

    def _refresh(self, block: bool) -> None:
        if block:
            version, replicas = ray_tpu.get(
                self.controller.poll_replicas.remote(
                    self.deployment_name, self._version, 2.0))
        else:
            version, replicas = ray_tpu.get(
                self.controller.get_replicas.remote(self.deployment_name))
        with self._lock:
            self._version = version
            self._replicas = replicas

    def _queue_len(self, rid: str, handle) -> int:
        now = time.monotonic()
        cached = self._qlen_cache.get(rid)
        if cached and now - cached[0] < _PROBE_CACHE_S:
            return cached[1]
        try:
            qlen = ray_tpu.get(handle.get_queue_len.remote(), timeout=1.0)
        except Exception:
            qlen = 1 << 30  # unprobeable replica loses the comparison
        with self._lock:
            self._qlen_cache[rid] = (now, qlen)
        return qlen

    def choose(self, args_blob: Optional[bytes] = None
               ) -> Tuple[str, Any]:
        """Pick a replica: two random candidates, shorter queue wins.
        ``args_blob`` (the serialized request) is ignored here but lets
        policy subclasses route on request CONTENT (prefix_router.py);
        retries re-enter choose() with the same blob, so content
        policies re-apply on every attempt."""
        deadline = time.monotonic() + 30.0
        block = False
        while True:
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                if len(replicas) == 1:
                    return replicas[0]
                a, b = self._rng.sample(replicas, 2)
                return a if (self._queue_len(*a) <= self._queue_len(*b)) \
                    else b
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment "
                    f"{self.deployment_name!r} after 30 s")
            self._refresh(block)
            block = True

    def submit(self, method_name: str, args_blob: bytes):
        """Route once and return (replica_id, ObjectRef); rejection is
        surfaced at get() time and retried by DeploymentResponse."""
        ROUTER_REQUESTS.inc(tags={"deployment": self.deployment_name})
        with tracing.span("route", component="serve.router",
                          tags={"deployment": self.deployment_name}):
            rid, handle = self.choose(args_blob)
            return rid, handle.handle_request.remote(method_name,
                                                     args_blob)

    def observe_latency(self, seconds: float) -> None:
        """Record one finished request's latency (called by
        DeploymentResponse.result, where the handle path's wait ends)."""
        REQUEST_LATENCY.observe(seconds,
                                tags={"deployment": self.deployment_name})

    def _admit_stream(self, method_name: str, args_blob: bytes,
                      item_timeout_s: Optional[float]):
        """Route a streaming request until a replica admits it; returns
        (kind, header, item_iterator). Runs under a routing span so the
        replica's actor task attaches to the request's trace; metrics
        cover admission (queue wait) and rejections."""
        t0 = time.monotonic()
        attempts = 0
        deadline = t0 + 60.0
        dep_tags = {"deployment": self.deployment_name}
        ROUTER_REQUESTS.inc(tags=dep_tags)
        with tracing.span("route", component="serve.router",
                          tags=dep_tags):
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"streaming request to {self.deployment_name} "
                        f"not admitted after {attempts} rejected "
                        "attempts")
                rid, handle = self.choose(args_blob)
                it = handle.handle_request_streaming.options(
                    num_returns="streaming").remote(method_name,
                                                    args_blob)
                try:
                    header = ray_tpu.get(it.next_ready(item_timeout_s),
                                         timeout=item_timeout_s)
                except StopIteration:
                    self._refresh(block=False)
                    continue
                except ray_tpu.exceptions.ActorError:
                    self._refresh(block=False)
                    continue
                kind = header.get("type")
                if kind == "rejected":
                    attempts += 1
                    ROUTER_REJECTIONS.inc(tags=dep_tags)
                    with self._lock:
                        self._qlen_cache.pop(rid, None)
                        self._reject_penalty[rid] = \
                            time.monotonic() + 1.0
                    time.sleep(min(0.05 * attempts, 0.5))
                    continue
                QUEUE_WAIT.observe(time.monotonic() - t0, tags=dep_tags)
                return t0, kind, header, it

    def stream(self, method_name: str, args_blob: bytes,
               item_timeout_s: Optional[float] = None):
        """Route a streaming request (reference: router streaming path,
        serve/_private/router.py handle streaming). Yields the replica's
        items after the header: a single ("single", value) item, or
        ("chunk", value) items as the handler produces them. Re-routes
        on rejection/replica death before any chunk was consumed."""
        t0, kind, header, it = self._admit_stream(
            method_name, args_blob, item_timeout_s)
        dep_tags = {"deployment": self.deployment_name}
        if kind == "single":
            REQUEST_LATENCY.observe(time.monotonic() - t0, tags=dep_tags)
            yield "single", header.get("data")
            return
        try:
            while True:
                try:
                    ref = it.next_ready(item_timeout_s)
                except StopIteration:
                    return
                item = ray_tpu.get(ref, timeout=item_timeout_s)
                yield "chunk", item.get("data")
        finally:
            REQUEST_LATENCY.observe(time.monotonic() - t0, tags=dep_tags)

    def fetch(self, method_name: str, args_blob: bytes,
              timeout: Optional[float]) -> Any:
        """Route + get with rejection retries (the blocking path)."""
        t0 = time.monotonic()
        attempts = 0
        deadline = (t0 + timeout) if timeout else None
        dep_tags = {"deployment": self.deployment_name}
        ROUTER_REQUESTS.inc(tags=dep_tags)
        with tracing.span("route", component="serve.router",
                          tags=dep_tags):
            while True:
                rid, handle = self.choose(args_blob)
                ref = handle.handle_request.remote(method_name, args_blob)
                try:
                    remaining = (max(0.001, deadline - time.monotonic())
                                 if deadline else None)
                    result = ray_tpu.get(ref, timeout=remaining)
                except ray_tpu.exceptions.ActorError:
                    self._refresh(block=False)  # replica died; new set
                    continue
                if not isinstance(result, Rejected):
                    REQUEST_LATENCY.observe(time.monotonic() - t0,
                                            tags=dep_tags)
                    return result
                attempts += 1
                ROUTER_REJECTIONS.inc(tags=dep_tags)
                with self._lock:
                    self._qlen_cache.pop(rid, None)
                    self._reject_penalty[rid] = time.monotonic() + 1.0
                if deadline and time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"request to {self.deployment_name} timed out "
                        f"after {attempts} rejected attempts")
                time.sleep(min(0.05 * attempts, 0.5))
