"""HTTP proxy: routes requests to ingress deployments.

Capability parity with the reference's proxy (reference:
python/ray/serve/_private/proxy.py:115,530,706 HTTP proxy — longest-
prefix route matching, JSON bodies, per-request routing through the
router). The reference runs uvicorn/ASGI proxy actors on every ingress
node; here a threaded stdlib HTTP server runs in the driver (or any
host) process — dependency-free and sufficient for single-host serving;
multi-host ingress fans out by starting one proxy per node.
"""

from __future__ import annotations

import json
import threading

from ray_tpu.devtools import locktrace
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.serve.admission import BackpressureError
from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Histogram

PROXY_REQUESTS = Counter(
    "ray_tpu_serve_proxy_requests_total",
    "HTTP requests through the serve proxy, by deployment and outcome",
    tag_keys=("deployment", "outcome"))
PROXY_LATENCY = Histogram(
    "ray_tpu_serve_proxy_latency_seconds",
    "Proxy-measured end-to-end HTTP request latency",
    tag_keys=("deployment",))


class _ProxyState:
    def __init__(self, controller):
        self.controller = controller
        self._routes: Dict[str, str] = {}
        self._lock = locktrace.traced_lock("serve.proxy")

    def refresh(self) -> None:
        routes = ray_tpu.get(self.controller.list_routes.remote())
        with self._lock:
            self._routes = dict(routes)

    def match(self, path: str) -> Optional[Tuple[str, str]]:
        """Longest-prefix match → (deployment_name, remaining_path)."""
        with self._lock:
            routes = dict(self._routes)
        best = None
        for prefix, dep in routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(
                    norm + ("" if norm == "/" else "/")) or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, dep)
        if best is None:
            return None
        prefix, dep = best
        rest = path[len(prefix):] if prefix != "/" else path
        return dep, rest or "/"


def _make_handler(state: _ProxyState):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def _respond(self, code: int, payload: Any,
                     extra_headers: Optional[Dict[str, str]] = None
                     ) -> None:
            body = (payload if isinstance(payload, (bytes, bytearray))
                    else json.dumps(payload).encode())
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (extra_headers or {}).items():
                self.send_header(key, value)
            self._send_traceparent()
            self.end_headers()
            self.wfile.write(body)

        def _send_traceparent(self) -> None:
            # Echo the request's trace so clients can retrieve the
            # distributed trace via /api/traces/<trace_id> — including
            # traces the proxy minted for header-less requests.
            ctx = getattr(self, "_trace_ctx", None)
            if ctx is not None:
                self.send_header("traceparent",
                                 tracing.format_traceparent(ctx))

        def _handle(self, body: Optional[dict]) -> None:
            # W3C trace context: continue the client's trace when a
            # valid traceparent header arrives, else mint a fresh root.
            # Everything downstream (router pick, replica execution,
            # nested .remote() calls, engine work) rides this context.
            parent_ctx = tracing.parse_traceparent(
                self.headers.get("traceparent"))
            with tracing.span("http_request", component="serve.proxy",
                              tags={"path": self.path.split("?")[0]},
                              parent=parent_ctx) as ctx:
                self._trace_ctx = ctx
                self._handle_traced(body)

        def _handle_traced(self, body: Optional[dict]) -> None:
            import time as _time
            t0 = _time.perf_counter()
            parsed = urllib.parse.urlparse(self.path)
            match = state.match(parsed.path)
            if match is None:
                state.refresh()
                match = state.match(parsed.path)
            if match is None:
                PROXY_REQUESTS.inc(tags={"deployment": "<no-route>",
                                         "outcome": "404"})
                self._respond(404, {"error": f"no route for {parsed.path}"})
                return
            dep, rest = match
            request: Dict[str, Any] = dict(
                urllib.parse.parse_qsl(parsed.query))
            if body:
                request.update(body)
            # Sub-path routing (e.g. the OpenAI /v1/* surface): expose
            # the remainder under the reserved "__path__" key. Always
            # strip any client-supplied value first — routing metadata
            # must come from the proxy, never the payload. Root requests
            # keep a pristine payload.
            request.pop("__path__", None)
            if rest != "/":
                request["__path__"] = rest
            streaming_started = False
            try:
                # Streaming-first protocol: the replica's header item
                # tells us whether the handler streamed (→ SSE/chunked
                # response, reference: serve/_private/proxy.py:706
                # streaming responses) or returned a value (→ JSON).
                from ray_tpu.core import serialization
                from ray_tpu.serve.handle import _get_router
                router = _get_router(dep, state.controller)
                blob = serialization.dumps(((request,), {}))
                gen = router.stream("__call__", blob, item_timeout_s=60.0)
                first = next(gen, None)
                if first is None:
                    self._respond(200, None)
                    return
                kind, value = first
                if kind == "single":
                    # Reserved "__status__": handlers set the HTTP code
                    # (e.g. 404 model_not_found on the OpenAI surface).
                    code = 200
                    if isinstance(value, dict) and "__status__" in value:
                        value = dict(value)
                        code = int(value.pop("__status__"))
                    self._respond(code, value)
                    PROXY_REQUESTS.inc(tags={"deployment": dep,
                                             "outcome": str(code)})
                    PROXY_LATENCY.observe(_time.perf_counter() - t0,
                                          tags={"deployment": dep})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self._send_traceparent()
                self.end_headers()
                streaming_started = True
                self._write_chunk(value)
                for _kind, chunk in gen:
                    self._write_chunk(chunk)
                PROXY_REQUESTS.inc(tags={"deployment": dep,
                                         "outcome": "200"})
                PROXY_LATENCY.observe(_time.perf_counter() - t0,
                                      tags={"deployment": dep})
            except BackpressureError as e:
                # Admission control shed this request (queue cap or
                # EWMA overload): 503 + Retry-After, the standard
                # please-back-off contract. Not an error outcome — the
                # system is doing exactly what it should under
                # overload — and never a latency observation.
                PROXY_REQUESTS.inc(tags={"deployment": dep,
                                         "outcome": "503"})
                if streaming_started:
                    return
                import math as _math
                retry_after = max(1, int(_math.ceil(e.retry_after_s)))
                try:
                    self._respond(
                        503,
                        {"error": "deployment overloaded",
                         "deployment": e.deployment,
                         "reason": e.reason,
                         "retry_after_s": e.retry_after_s},
                        extra_headers={"Retry-After": str(retry_after)})
                except (OSError, ValueError):
                    pass
            except Exception as e:  # noqa: BLE001 — surface as 500
                PROXY_REQUESTS.inc(tags={"deployment": dep,
                                         "outcome": "error"})
                if streaming_started:
                    return  # headers sent: a clean close, never a second
                           # status line into the SSE body
                try:
                    self._respond(500, {"error": str(e)})
                except (OSError, ValueError):
                    pass

        def _write_chunk(self, chunk: Any) -> None:
            if isinstance(chunk, (bytes, bytearray)):
                data = bytes(chunk)
            elif isinstance(chunk, str):
                data = chunk.encode()
            else:
                data = (json.dumps(chunk) + "\n").encode()
            self.wfile.write(data)
            self.wfile.flush()

        def do_GET(self):  # noqa: N802
            self._handle(None)

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            try:
                body = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                body = {"body": raw.decode("utf-8", "replace")}
            self._handle(body)

    return Handler


class HttpProxy:
    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000):
        self.state = _ProxyState(controller)
        self.server = ThreadingHTTPServer((host, port),
                                          _make_handler(self.state))
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
