"""Replica actor: hosts one copy of a deployment's callable.

Capability parity with the reference's replica (reference:
python/ray/serve/_private/replica.py:492,1138 ReplicaActor,
handle_request_with_rejection:831 — backpressure via
max_ongoing_requests; queue-length probes for the router; request
metrics for autoscaling; reconfigure(user_config); multiplexed model
LRU).
"""

from __future__ import annotations

import threading

from ray_tpu.devtools import locktrace
import time
from typing import Any, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Gauge, Histogram

# Replica-side instrumentation (reference: replica request metrics
# consumed by autoscaling + the dashboard). Updates are forwarded
# worker→driver through the control plane — request-rate, not hot-loop.
REPLICA_REQUESTS = Counter(
    "ray_tpu_serve_replica_requests_total",
    "Requests executed on replicas, by deployment and outcome",
    tag_keys=("deployment", "outcome"))
REPLICA_LATENCY = Histogram(
    "ray_tpu_serve_replica_request_seconds",
    "Replica-measured request execution time", tag_keys=("deployment",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0])
REPLICA_ONGOING = Gauge(
    "ray_tpu_serve_replica_ongoing_requests",
    "In-flight requests on one replica",
    tag_keys=("deployment", "replica"))


class Rejected:
    """Sentinel returned (not raised — task errors are wrapped in
    TaskError on the wire) when a replica is at max_ongoing_requests;
    the router retries on another replica."""

    def __reduce__(self):
        return (Rejected, ())


class Replica:
    def __init__(self, deployment_name: str, replica_id: str,
                 callable_blob: bytes, init_args_blob: bytes,
                 max_ongoing_requests: int,
                 user_config: Optional[dict] = None,
                 multiplex_max_models: int = 3):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        cls_or_fn = serialization.loads(callable_blob)
        init_args, init_kwargs = serialization.loads(init_args_blob)
        if isinstance(cls_or_fn, type):
            self.callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.callable = cls_or_fn
        self.max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total = 0
        self._lock = locktrace.traced_lock("serve.replica")
        # sliding window of (t, ongoing) samples for autoscaling
        self._metric_samples = []
        self._multiplexed: "dict[str, Any]" = {}  # model_id -> model (LRU)
        self._multiplex_max = multiplex_max_models
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path --

    def handle_request(self, method_name: str, args_blob: bytes) -> Any:
        from ray_tpu.serve.admission import BackpressureError, Shed
        with self._lock:
            if self._ongoing >= self.max_ongoing:
                REPLICA_REQUESTS.inc(
                    tags={"deployment": self.deployment_name,
                          "outcome": "rejected"})
                return Rejected()
            self._ongoing += 1
            self._total += 1
        t0 = time.perf_counter()
        outcome = "ok"
        try:
            with tracing.span("handle_request",
                              component="serve.replica",
                              tags={"deployment": self.deployment_name,
                                    "replica": self.replica_id,
                                    "method": method_name}):
                args, kwargs = serialization.loads(args_blob)
                fn = getattr(self.callable, method_name, self.callable)
                result = fn(*args, **kwargs)
                import inspect
                if inspect.iscoroutine(result):
                    import asyncio
                    result = asyncio.run(result)
                return result
        except BackpressureError as exc:
            # The handler itself shed (e.g. the LLM engine's reject-
            # before-enqueue hook). A sentinel — not a raised error —
            # so the router distinguishes "workload overloaded, tell
            # the client" from a replica crash it should retry.
            outcome = "shed"
            return Shed(exc.retry_after_s, exc.reason)
        except BaseException:
            outcome = "error"
            raise
        finally:
            with self._lock:
                self._ongoing -= 1
                ongoing = self._ongoing
                self._metric_samples.append((time.monotonic(), self._ongoing))
                if len(self._metric_samples) > 1000:
                    self._metric_samples = self._metric_samples[-500:]
            self._report_request_metrics(outcome,
                                         time.perf_counter() - t0,
                                         ongoing)

    def handle_control_request(self, method_name: str,
                               args_blob: bytes) -> Any:
        """Control-plane entry point: runs a method on the wrapped
        callable WITHOUT the max_ongoing_requests gate, the Rejected
        sentinel, or the Shed translation. For operations that must
        reach the replica precisely when it is saturated (weight
        pushes, reconfiguration): the data-plane path would return
        Rejected, which only the router path retries — a direct caller
        that ignores the sentinel silently loses the call."""
        with self._lock:
            self._total += 1
        with tracing.span("handle_control_request",
                          component="serve.replica",
                          tags={"deployment": self.deployment_name,
                                "replica": self.replica_id,
                                "method": method_name}):
            args, kwargs = serialization.loads(args_blob)
            fn = getattr(self.callable, method_name, self.callable)
            result = fn(*args, **kwargs)
            import inspect
            if inspect.iscoroutine(result):
                import asyncio
                result = asyncio.run(result)
            return result

    def _report_request_metrics(self, outcome: str, seconds: float,
                                ongoing: int) -> None:
        tags = {"deployment": self.deployment_name}
        REPLICA_REQUESTS.inc(tags={**tags, "outcome": outcome})
        if outcome != "shed":
            # shed requests never executed: their (near-zero) timings
            # would drag p50/p99 down exactly when overload makes the
            # latency series most load-bearing
            REPLICA_LATENCY.observe(seconds, tags=tags)
        REPLICA_ONGOING.set(float(ongoing),
                            tags={**tags, "replica": self.replica_id})

    def handle_request_streaming(self, method_name: str, args_blob: bytes):
        """Streaming request path (called with num_returns="streaming";
        reference: replica.py:793 handle_request_streaming). Yields a
        header item first:
          {"type": "rejected"}               — at max_ongoing_requests
          {"type": "single", "data": value}  — handler returned a value
          {"type": "stream"}                 — handler is a generator;
                                               chunks follow, one per item
        Backpressure accounting covers the whole stream lifetime. A
        handler that raises BackpressureError (LLM engine saturation)
        yields a {"type": "shed", "retry_after_s", "reason"} header —
        the router forwards that verdict to the client instead of
        retrying another replica.
        """
        import inspect

        from ray_tpu.serve.admission import BackpressureError

        with self._lock:
            admitted = self._ongoing < self.max_ongoing
            if admitted:
                self._ongoing += 1
                self._total += 1
        if not admitted:
            # yield OUTSIDE the lock: a generator suspension while
            # holding it would block every other request thread.
            REPLICA_REQUESTS.inc(
                tags={"deployment": self.deployment_name,
                      "outcome": "rejected"})
            yield {"type": "rejected"}
            return
        t0 = time.perf_counter()
        outcome = "ok"
        try:
            with tracing.span("handle_request_streaming",
                              component="serve.replica",
                              tags={"deployment": self.deployment_name,
                                    "replica": self.replica_id,
                                    "method": method_name}):
                args, kwargs = serialization.loads(args_blob)
                fn = getattr(self.callable, method_name, self.callable)
                result = fn(*args, **kwargs)
                if inspect.iscoroutine(result):
                    import asyncio
                    result = asyncio.run(result)
                if inspect.isgenerator(result):
                    yield {"type": "stream"}
                    for chunk in result:
                        yield {"type": "chunk", "data": chunk}
                elif inspect.isasyncgen(result):
                    import asyncio

                    yield {"type": "stream"}
                    loop = asyncio.new_event_loop()
                    try:
                        while True:
                            try:
                                chunk = loop.run_until_complete(
                                    result.__anext__())
                            except StopAsyncIteration:
                                break
                            yield {"type": "chunk", "data": chunk}
                    finally:
                        loop.close()
                else:
                    yield {"type": "single", "data": result}
        except BackpressureError as exc:
            outcome = "shed"
            yield {"type": "shed", "retry_after_s": exc.retry_after_s,
                   "reason": exc.reason}
        except BaseException:
            outcome = "error"
            raise
        finally:
            with self._lock:
                self._ongoing -= 1
                ongoing = self._ongoing
                self._metric_samples.append((time.monotonic(), self._ongoing))
                if len(self._metric_samples) > 1000:
                    self._metric_samples = self._metric_samples[-500:]
            self._report_request_metrics(outcome,
                                         time.perf_counter() - t0,
                                         ongoing)

    # -- router/controller probes --

    def get_queue_len(self) -> int:
        return self._ongoing

    def get_metrics(self, window_s: float = 2.0) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            recent = [v for t, v in self._metric_samples
                      if now - t <= window_s]
            ongoing = self._ongoing
        avg = (sum(recent) / len(recent)) if recent else float(ongoing)
        return {"ongoing": float(ongoing), "avg_ongoing": avg,
                "total": float(self._total)}

    def check_health(self) -> bool:
        checker = getattr(self.callable, "check_health", None)
        if checker is not None:
            checker()
        return True

    def reconfigure(self, user_config: dict) -> None:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    # -- multiplexing (reference: serve/multiplex.py model LRU) --

    def load_multiplexed(self, model_id: str, loader_blob: bytes) -> None:
        with self._lock:
            if model_id in self._multiplexed:
                # LRU touch
                self._multiplexed[model_id] = \
                    self._multiplexed.pop(model_id)
                return
        loader = serialization.loads(loader_blob)
        model = loader(model_id)  # expensive load outside the lock
        with self._lock:
            if len(self._multiplexed) >= self._multiplex_max:
                evict = next(iter(self._multiplexed))
                del self._multiplexed[evict]
            self._multiplexed[model_id] = model

    def get_multiplexed_model_ids(self) -> list:
        return list(self._multiplexed)

    def get_multiplexed_model(self, model_id: str):
        return self._multiplexed.get(model_id)

    def prepare_for_shutdown(self) -> None:
        stopper = getattr(self.callable, "__del__", None)
        _ = stopper
