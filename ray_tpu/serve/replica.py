"""Replica actor: hosts one copy of a deployment's callable.

Capability parity with the reference's replica (reference:
python/ray/serve/_private/replica.py:492,1138 ReplicaActor,
handle_request_with_rejection:831 — backpressure via
max_ongoing_requests; queue-length probes for the router; request
metrics for autoscaling; reconfigure(user_config); multiplexed model
LRU).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core import serialization


class Rejected:
    """Sentinel returned (not raised — task errors are wrapped in
    TaskError on the wire) when a replica is at max_ongoing_requests;
    the router retries on another replica."""

    def __reduce__(self):
        return (Rejected, ())


class Replica:
    def __init__(self, deployment_name: str, replica_id: str,
                 callable_blob: bytes, init_args_blob: bytes,
                 max_ongoing_requests: int,
                 user_config: Optional[dict] = None,
                 multiplex_max_models: int = 3):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        cls_or_fn = serialization.loads(callable_blob)
        init_args, init_kwargs = serialization.loads(init_args_blob)
        if isinstance(cls_or_fn, type):
            self.callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.callable = cls_or_fn
        self.max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        # sliding window of (t, ongoing) samples for autoscaling
        self._metric_samples = []
        self._multiplexed: "dict[str, Any]" = {}  # model_id -> model (LRU)
        self._multiplex_max = multiplex_max_models
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path --

    def handle_request(self, method_name: str, args_blob: bytes) -> Any:
        with self._lock:
            if self._ongoing >= self.max_ongoing:
                return Rejected()
            self._ongoing += 1
            self._total += 1
        try:
            args, kwargs = serialization.loads(args_blob)
            fn = getattr(self.callable, method_name, self.callable)
            result = fn(*args, **kwargs)
            import inspect
            if inspect.iscoroutine(result):
                import asyncio
                result = asyncio.run(result)
            return result
        finally:
            with self._lock:
                self._ongoing -= 1
                self._metric_samples.append((time.monotonic(), self._ongoing))
                if len(self._metric_samples) > 1000:
                    self._metric_samples = self._metric_samples[-500:]

    def handle_request_streaming(self, method_name: str, args_blob: bytes):
        """Streaming request path (called with num_returns="streaming";
        reference: replica.py:793 handle_request_streaming). Yields a
        header item first:
          {"type": "rejected"}               — at max_ongoing_requests
          {"type": "single", "data": value}  — handler returned a value
          {"type": "stream"}                 — handler is a generator;
                                               chunks follow, one per item
        Backpressure accounting covers the whole stream lifetime.
        """
        import inspect

        with self._lock:
            admitted = self._ongoing < self.max_ongoing
            if admitted:
                self._ongoing += 1
                self._total += 1
        if not admitted:
            # yield OUTSIDE the lock: a generator suspension while
            # holding it would block every other request thread.
            yield {"type": "rejected"}
            return
        try:
            args, kwargs = serialization.loads(args_blob)
            fn = getattr(self.callable, method_name, self.callable)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                import asyncio
                result = asyncio.run(result)
            if inspect.isgenerator(result):
                yield {"type": "stream"}
                for chunk in result:
                    yield {"type": "chunk", "data": chunk}
            elif inspect.isasyncgen(result):
                import asyncio

                yield {"type": "stream"}
                loop = asyncio.new_event_loop()
                try:
                    while True:
                        try:
                            chunk = loop.run_until_complete(
                                result.__anext__())
                        except StopAsyncIteration:
                            break
                        yield {"type": "chunk", "data": chunk}
                finally:
                    loop.close()
            else:
                yield {"type": "single", "data": result}
        finally:
            with self._lock:
                self._ongoing -= 1
                self._metric_samples.append((time.monotonic(), self._ongoing))
                if len(self._metric_samples) > 1000:
                    self._metric_samples = self._metric_samples[-500:]

    # -- router/controller probes --

    def get_queue_len(self) -> int:
        return self._ongoing

    def get_metrics(self, window_s: float = 2.0) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            recent = [v for t, v in self._metric_samples
                      if now - t <= window_s]
            ongoing = self._ongoing
        avg = (sum(recent) / len(recent)) if recent else float(ongoing)
        return {"ongoing": float(ongoing), "avg_ongoing": avg,
                "total": float(self._total)}

    def check_health(self) -> bool:
        checker = getattr(self.callable, "check_health", None)
        if checker is not None:
            checker()
        return True

    def reconfigure(self, user_config: dict) -> None:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    # -- multiplexing (reference: serve/multiplex.py model LRU) --

    def load_multiplexed(self, model_id: str, loader_blob: bytes) -> None:
        if model_id in self._multiplexed:
            self._multiplexed[model_id] = self._multiplexed.pop(model_id)
            return
        loader = serialization.loads(loader_blob)
        if len(self._multiplexed) >= self._multiplex_max:
            evict = next(iter(self._multiplexed))
            del self._multiplexed[evict]
        self._multiplexed[model_id] = loader(model_id)

    def get_multiplexed_model_ids(self) -> list:
        return list(self._multiplexed)

    def get_multiplexed_model(self, model_id: str):
        return self._multiplexed.get(model_id)

    def prepare_for_shutdown(self) -> None:
        stopper = getattr(self.callable, "__del__", None)
        _ = stopper
