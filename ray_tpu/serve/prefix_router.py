"""Prefix-aware request routing for LLM serving.

Reference: python/ray/llm/_internal/serve/routing_policies/
prefix_aware/{prefix_aware_router.py,prefix_tree.py} —
PrefixCacheAffinityRouter extends pow-2 with a prefix tree: when
replica load is balanced, requests route to the replica with the
highest prompt-prefix match (KV/prefix-cache locality); when load is
imbalanced, plain pow-2 wins; low match rates fall back too. The tree
records prompt -> replica after each routing decision and evicts by
total stored characters.

Here the tree lives inside the driver-side router (the reference keeps
it in a dedicated actor because many proxies share it; this runtime
has one router per driver process — a stated simplification)."""

from __future__ import annotations

import threading

from ray_tpu.devtools import locktrace
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.serve.router import Router

# chunked trie: bounded depth regardless of prompt length
_CHUNK = 16
_MAX_INSERT_CHARS = 2048
# non-string sentinel key for each node's replica set: prompt chunks
# are strings, so arbitrary prompt text (even one containing the
# sentinel's repr) can never collide with it
_RIDS = ("__rids__",)


class PrefixTree:
    """Chunked character trie mapping prompt prefixes to the replicas
    that served them (reference: prefix_tree.py PrefixTreeActor, minus
    the actor wrapper)."""

    def __init__(self, eviction_threshold_chars: int = 400_000):
        self._root: Dict[str, Any] = {}
        self._lock = locktrace.traced_lock("serve.prefix_router")
        self._chars = 0
        self._threshold = eviction_threshold_chars

    def insert(self, text: str, replica_id: str) -> None:
        text = text[:_MAX_INSERT_CHARS]
        with self._lock:
            if self._chars + len(text) > self._threshold:
                # Bounded memory: reset when full (the reference prunes
                # LRU leaves on a timer; a reset keeps the same bound
                # with an occasional cold tree — stated simplification)
                self._root = {}
                self._chars = 0
            node = self._root
            for i in range(0, len(text), _CHUNK):
                chunk = text[i:i + _CHUNK]
                child = node.get(chunk)
                if child is None:
                    child = {_RIDS: set()}
                    node[chunk] = child
                    self._chars += len(chunk)
                child[_RIDS].add(replica_id)
                node = child

    def match(self, text: str) -> Dict[str, int]:
        """replica id -> matched prefix chars (deepest node containing
        the replica along this text's path)."""
        out: Dict[str, int] = {}
        with self._lock:
            node = self._root
            depth = 0
            for i in range(0, len(text), _CHUNK):
                child = node.get(text[i:i + _CHUNK])
                if child is None:
                    break
                depth += len(text[i:i + _CHUNK])
                for rid in child[_RIDS]:
                    out[rid] = depth
                node = child
        return out

    def drop_replica(self, replica_id: str) -> None:
        """Forget a dead replica everywhere (its cache died with it)."""
        with self._lock:
            stack = [self._root]
            while stack:
                node = stack.pop()
                for key, child in node.items():
                    if key is _RIDS:
                        child.discard(replica_id)
                    else:
                        stack.append(child)


def extract_prompt(request: Any) -> Optional[str]:
    """Pull routable text out of an OpenAI-shaped request dict."""
    if not isinstance(request, dict):
        return None
    prompt = request.get("prompt")
    if isinstance(prompt, str) and prompt:
        return prompt
    messages = request.get("messages")
    if isinstance(messages, list) and messages:
        parts: List[str] = []
        for m in messages:
            content = m.get("content") if isinstance(m, dict) else None
            if isinstance(content, str):
                parts.append(content)
        if parts:
            return "\n".join(parts)
    return None


class PrefixAwareRouter(Router):
    """Pow-2 router + prefix-cache affinity (reference:
    prefix_aware_router.py PrefixCacheAffinityRouter):

    - balanced load + match rate >= match_rate_threshold -> the replica
      with the deepest prompt-prefix match wins (cache locality);
    - otherwise plain pow-2.
    Every routed prompt is inserted into the tree afterward."""

    imbalanced_threshold = 10     # queue-length gap = "imbalanced"
    match_rate_threshold = 0.10   # matched chars / prompt chars

    def __init__(self, deployment_name: str, controller):
        super().__init__(deployment_name, controller)
        self.tree = PrefixTree()

    def choose(self, args_blob: Optional[bytes] = None
               ) -> Tuple[str, Any]:
        """All Router paths (submit/stream/fetch + their retries) come
        through here with the serialized request."""
        text = None
        if args_blob is not None:
            from ray_tpu.core import serialization
            try:
                args, _kwargs = serialization.loads(args_blob)
                if args:
                    text = extract_prompt(args[0])
            except Exception:  # graftlint: disable=GL004
                pass  # unroutable request body: plain pow-2 applies
        rid, handle = self._choose_for_prompt(text)
        if text:
            self.tree.insert(text, rid)
        return rid, handle

    def _refresh(self, block: bool) -> None:
        """Replica-set changes also purge dead replicas from the tree
        (their prefix caches died with them)."""
        with self._lock:
            before = {rid for rid, _ in self._replicas}
        super()._refresh(block)
        with self._lock:
            after = {rid for rid, _ in self._replicas}
        for rid in before - after:
            self.tree.drop_replica(rid)

    def _choose_for_prompt(self, text: Optional[str]
                           ) -> Tuple[str, Any]:
        if not text:
            return super().choose()
        with self._lock:
            replicas = dict(self._replicas)
        if len(replicas) <= 1:
            return super().choose()
        matches = {rid: n for rid, n in self.tree.match(text).items()
                   if rid in replicas}
        if not matches:
            return super().choose()
        best_rid = max(matches, key=lambda r: matches[r])
        if matches[best_rid] / max(len(text), 1) \
                < self.match_rate_threshold:
            return super().choose()
        # A replica that recently rejected sits out affinity: without
        # this, a saturated cache-affine replica whose queue gap never
        # crosses imbalanced_threshold livelocks retries while the
        # rest of the fleet idles. The penalty score decays toward
        # zero, so a recovered replica regains its affinity traffic.
        if self.rejection_penalty(best_rid) >= 1.0:
            return super().choose()
        # Balance check probes ONLY best + two sampled candidates (the
        # reference pow-2 discipline): probing every replica would put
        # a dead replica's 1s probe timeout on each request.
        others = [rid for rid in replicas if rid != best_rid]
        sample = self._rng.sample(others, min(2, len(others)))
        qlens = {rid: self._queue_len(rid, replicas[rid])
                 for rid in [best_rid] + sample}
        if (qlens[best_rid] - min(qlens.values())
                > self.imbalanced_threshold):
            return super().choose()  # imbalanced: load wins over cache
        return best_rid, replicas[best_rid]
